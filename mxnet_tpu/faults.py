"""Seeded fault-injection registry: the failure twin of mxstress's chaos locks.

PR 3's ``ChaosScheduler`` perturbs lock *schedules*; this module injects
*failures* — the cluster conditions the reference's parameter-server design
is built to survive (MXNet, arXiv:1512.01274 §5; TensorFlow's
checkpoint/restore fault-tolerance story, arXiv:1605.08695 §4.3): torn
checkpoint writes, dying DataLoader workers, failed device transfers, flaky
kvstore pushes, and serving backends that start throwing.

Named injection sites (``KNOWN_SITES``) are wired into the runtime's I/O and
execution boundaries; production code calls ``fault_point(site, **info)``
which is a no-op unless a :class:`FaultPlan` is active.  A plan is a seeded
set of rules — *which* sites fail, *how* (transient / fatal / torn-write /
process crash), with what probability, and how many times — so every chaos
run is reproducible from its seed.

Fault kinds
-----------
``transient``
    Raises :class:`TransientFault` — the retryable class.  Every recoverable
    site in the framework wraps its boundary in :func:`mxnet_tpu.util.retry`,
    so a transient fault is absorbed invisibly (modulo latency) unless it
    fires more times than the retry budget.
``fatal``
    Raises :class:`FatalFault` — not retryable; models a persistent backend
    failure.  Surfaces as an ERROR/exception at the call site (and trips the
    serving circuit breaker).
``crash``
    Raises :class:`SimulatedCrash` — a ``BaseException`` so no recovery code
    can accidentally swallow it; it models ``kill -9`` mid-operation.  The
    crash-consistency sweeps kill a checkpoint write at every such point and
    assert that restore still finds the newest *complete* checkpoint.
``truncate``
    Torn-write modeling for file sites: truncates the in-progress file
    (``info["fileobj"]``) at a seeded byte offset, then crashes.  Sites that
    pass no file handle degrade to a plain crash.

Usage::

    plan = faults.FaultPlan(seed=7)
    plan.add("serving.predict", kind="transient", p=0.3, times=5)
    plan.add("checkpoint.write", kind="crash", after=2)
    with faults.plan(plan):
        ...  # every thread sees the plan; counters in plan.hits / plan.fired

See docs/ROBUSTNESS.md for the full site catalog and the retry/backoff
policy table; ``mxnet_tpu/analysis/schedule.py`` (``faults``/``crash``
scenarios) and tests/test_faults.py are the standing consumers.
"""
from __future__ import annotations

import contextlib
import random
import threading

from .base import MXNetError

__all__ = ["InjectedFault", "TransientFault", "FatalFault", "SimulatedCrash",
           "FaultPlan", "FaultRule", "plan", "active_plan", "fault_point",
           "is_retryable", "KNOWN_SITES"]

# the fault-site catalog (docs/ROBUSTNESS.md keeps the prose version).
# fault_point() rejects unknown names so a typo at an injection site fails
# loudly in the chaos suite instead of silently never firing.
KNOWN_SITES = frozenset({
    # checkpoint file writes (util.write_atomic: every atomic write —
    # .params / -symbol.json / .states / -manifest.json — passes these)
    "checkpoint.write",       # after each chunk lands in the tmp file
    "checkpoint.replace",     # tmp fully written+fsynced, BEFORE os.replace
    "checkpoint.replaced",    # after os.replace, before the caller returns
    # input pipeline
    "dataloader.worker",      # start of a pool worker's batch load
    "device_feed.put",        # start of DeviceFeed's device staging
    # gradient aggregation
    "kvstore.push",
    "kvstore.pull",
    # serving
    "serving.predict",        # ServableModel.execute, before the XLA call
    # fleet routing (serving/fleet.py): before the router hands one attempt
    # to the chosen replica.  A "crash" here models the REPLICA's death as
    # observed by the router — the router is the surviving process, so it
    # (exceptionally) catches SimulatedCrash at this one site, marks the
    # replica DEAD, and fails the request over; see FleetRouter.predict.
    "fleet.replica",
    # rolling deployment (serving/deploy.py): the controller's swap
    # pipeline.  A "crash" at any of these models the CONTROLLER dying
    # mid-swap; the fleet must keep serving the old generation.
    "deploy.resolve",         # before loading the resolved checkpoint
    "deploy.warmup",          # before staging one (name, replica) copy
    "deploy.cutover",         # before fencing the old placements
    "deploy.commit",          # before the atomic routing flip
})


class InjectedFault(MXNetError):
    """Base class of every injected failure (except SimulatedCrash)."""


class TransientFault(InjectedFault):
    """A retryable injected failure (flaky transfer, worker blip)."""


class FatalFault(InjectedFault):
    """A non-retryable injected failure (persistent backend breakage)."""


class SimulatedCrash(BaseException):
    """Models process death (``kill -9``) at a fault point.

    Deliberately a ``BaseException``: recovery code written as
    ``except Exception`` must not be able to swallow a crash — after a real
    SIGKILL there is nobody left to run the handler.  Only the chaos harness
    (which plays the role of the *next* process) catches it — plus one
    documented exception: at the ``fleet.replica`` site the crash models a
    *replica's* death and the FleetRouter is the surviving observer, so the
    router catches it there and converts it into replica-death handling.
    """


class FaultRule:
    """One (site pattern, kind, probability, window) injection rule.

    ``site`` is an exact site name or a ``"prefix.*"`` glob.  The rule fires
    on hits ``after <= hit_index`` (per matching site, 0-based), each with
    probability ``p``, at most ``times`` times total (None = unlimited).
    """

    __slots__ = ("site", "kind", "p", "after", "times", "fired")

    _KINDS = ("transient", "fatal", "crash", "truncate")

    def __init__(self, site, kind="transient", p=1.0, after=0, times=None):
        if kind not in self._KINDS:
            raise ValueError("unknown fault kind %r (one of %s)"
                             % (kind, "/".join(self._KINDS)))
        if not (site.endswith(".*") or site in KNOWN_SITES):
            raise ValueError("unknown fault site %r; known: %s"
                             % (site, ", ".join(sorted(KNOWN_SITES))))
        self.site = site
        self.kind = kind
        self.p = float(p)
        self.after = int(after)
        self.times = times if times is None else int(times)
        self.fired = 0

    def matches(self, site):
        if self.site.endswith(".*"):
            return site.startswith(self.site[:-1])
        return site == self.site

    def __repr__(self):
        return ("FaultRule(site=%r, kind=%r, p=%g, after=%d, times=%r, "
                "fired=%d)" % (self.site, self.kind, self.p, self.after,
                               self.times, self.fired))


class FaultPlan:
    """A seeded, thread-safe set of fault rules plus hit/fire accounting.

    ``hits`` counts every ``fault_point`` pass per site while the plan is
    active (fired or not) — the crash sweeps use it to enumerate kill
    points; ``fired`` counts injections actually delivered.
    """

    def __init__(self, seed=0, rules=()):
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._rules = []
        self.hits = {}      # site -> fault_point passes (guarded by _lock)
        self.fired = {}     # site -> injections delivered (guarded by _lock)
        for r in rules:
            self.add(**r) if isinstance(r, dict) else self.add_rule(r)

    def add(self, site, kind="transient", p=1.0, after=0, times=None):
        """Append a rule (see :class:`FaultRule`); returns self for chaining."""
        return self.add_rule(FaultRule(site, kind=kind, p=p, after=after,
                                       times=times))

    def add_rule(self, rule):
        with self._lock:
            self._rules.append(rule)
        return self

    def hit_count(self, site_prefix=""):
        """Total ``fault_point`` passes for sites matching the prefix."""
        with self._lock:
            return sum(n for s, n in self.hits.items()
                       if s.startswith(site_prefix))

    def fired_count(self, site_prefix=""):
        with self._lock:
            return sum(n for s, n in self.fired.items()
                       if s.startswith(site_prefix))

    def consult(self, site):
        """Record a hit; return the kind to inject at this pass (or None).

        The first matching rule whose window and probability admit the hit
        wins; its ``fired`` counter and the plan's ``fired`` tally bump.
        """
        with self._lock:
            index = self.hits.get(site, 0)
            self.hits[site] = index + 1
            for rule in self._rules:
                if not rule.matches(site) or index < rule.after:
                    continue
                if rule.times is not None and rule.fired >= rule.times:
                    continue
                if rule.p < 1.0 and self._rng.random() >= rule.p:
                    continue
                rule.fired += 1
                self.fired[site] = self.fired.get(site, 0) + 1
                return rule.kind
        return None

    def truncate_offset(self, written):
        """Seeded torn-write offset in [0, written) for a truncate fault."""
        with self._lock:
            return self._rng.randrange(max(1, written))


# the active plan is process-global: fault points run on worker threads
# (serving batchers, DeviceFeed producers, pool workers) that must see the
# plan the test thread installed.  Reads are a single atomic ref load;
# writes go through _ACTIVE_LOCK.
_ACTIVE_LOCK = threading.Lock()
_ACTIVE = None


def active_plan():
    """The currently installed FaultPlan, or None."""
    return _ACTIVE


@contextlib.contextmanager
def plan(fault_plan):
    """Install ``fault_plan`` for the scope (all threads); restores on exit."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        prev, _ACTIVE = _ACTIVE, fault_plan
    try:
        yield fault_plan
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE = prev


def fault_point(site, **info):
    """Declare a named injection site.  No-op without an active plan.

    ``info`` is site-specific context; file sites pass ``fileobj`` and
    ``written`` so truncate faults can tear the in-progress file at a
    seeded byte offset.
    """
    active = _ACTIVE
    if active is None:
        return
    if site not in KNOWN_SITES:
        raise ValueError("fault_point(%r): unregistered site; add it to "
                         "faults.KNOWN_SITES" % site)
    kind = active.consult(site)
    if kind is None:
        return
    if kind == "transient":
        raise TransientFault("injected transient fault at %s" % site)
    if kind == "fatal":
        raise FatalFault("injected fatal fault at %s" % site)
    if kind == "truncate":
        fobj = info.get("fileobj")
        written = int(info.get("written", 0))
        if fobj is not None and written > 0:
            off = active.truncate_offset(written)
            fobj.flush()
            fobj.truncate(off)
        raise SimulatedCrash("injected torn write + crash at %s" % site)
    raise SimulatedCrash("injected crash at %s" % site)


def is_retryable(exc):
    """Is this exception in the retry-absorbable class?

    Transient injected faults are; fatal faults, simulated crashes, and
    ordinary exceptions are not (callers opt real exception types into
    retry explicitly via ``util.retry(retryable=...)``).
    """
    return isinstance(exc, TransientFault)
