"""Executor: compiled evaluation of a Symbol graph.

Reference: src/executor/graph_executor.cc — GraphExecutor::Init runs nnvm
passes (shape/type infer, PlanMemory, AttachOpExecs, InitCachedOps) then
Forward/Backward replay cached engine ops (:64-93, :1318).

TPU-native: "Init" = trace the DAG into one JAX function; jit compiles the
whole graph as a single XLA module (forward) and jax.vjp provides backward —
XLA's buffer assignment replaces PlanMemory, fusion replaces op bulking, and
donation replaces the shared-memory-pool trick (graph_executor.cc:927).
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray, _wrap, zeros as nd_zeros
from .ops.registry import get_op
from . import autograd


class Executor:
    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None):
        from .context import current_context
        self._symbol = symbol
        self._ctx = ctx or current_context()
        # manual model parallelism (reference graph_executor.cc:908
        # AssignContext): ops whose ctx_group attr maps to a Context run on
        # that device, with transfers at group boundaries (the
        # _CrossDeviceCopy analog is jax.device_put between groups)
        self._group2ctx = dict(group2ctx) if group2ctx else None
        self._group2dev = {name: c.jax_device()
                           for name, c in (group2ctx or {}).items()}
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        if isinstance(args, (list, tuple)):
            args = dict(zip(self.arg_names, args))
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(self.aux_names, aux_states))
        self.arg_dict = dict(args)
        self.aux_dict = dict(aux_states or {})
        self._aux_update_names = []  # set by _build_fn(is_train=True)
        self._aux_tail = ()
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(self.arg_names, args_grad))
        self.grad_dict = dict(args_grad) if args_grad else {}
        if isinstance(grad_req, str):
            self.grad_req = {n: grad_req for n in self.arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self.grad_req = dict(zip(self.arg_names, grad_req))
        else:
            self.grad_req = dict(grad_req)
        self.outputs = []
        self._fwd_train = None
        self._fwd_infer = None
        self._vjp = None
        self._jit_train_fwd = None
        self._jit_train_bwd = None
        self._jit_wrt = None       # wrt snapshot the jitted pair was built for
        self._monitor_callback = None

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self.arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self.arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self.aux_names]

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    # ------------------------------------------------------------------
    # ops whose (out, mean, var) training outputs fold into the moving-stat
    # aux inputs [3]=moving_mean, [4]=moving_var (batch_norm.cc:118-140:
    # moving = moving * momentum + batch * (1 - momentum))
    _BN_AUX_OPS = frozenset(("BatchNorm", "_contrib_SyncBatchNorm"))

    def _build_fn(self, is_train):
        """Trace the DAG into fn(arg_vals_list, aux_vals_list, keys) ->
        outs + updated-aux tail.

        The reference's BatchNorm MUTATES its moving_mean/moving_var aux
        states during every training forward; this pure trace instead
        APPENDS each touched aux's updated value after the graph outputs,
        and forward() writes the tail back into aux_dict — without this,
        Module-trained BN nets kept their init (0, 1) running stats and
        normalized garbage at inference (round-5 audit find)."""
        sym = self._symbol
        nodes = sym._topo_nodes()
        arg_order = {n: i for i, n in enumerate(self.arg_names)}
        aux_order = {n: i for i, n in enumerate(self.aux_names)}
        rng_nodes = [n for n in nodes
                     if n.op is not None and get_op(n.op).rng_for(n.attrs)]
        rng_index = {id(n): i for i, n in enumerate(rng_nodes)}

        bn_nodes = []
        if is_train:
            aux_update_names = []
            for n in nodes:
                if (n.op in self._BN_AUX_OPS and len(n.inputs) >= 5
                        and not n.attrs.get("use_global_stats", False)):
                    mm, mv = n.inputs[3][0], n.inputs[4][0]
                    if mm.name in aux_order and mv.name in aux_order:
                        bn_nodes.append(n)
                        aux_update_names += [mm.name, mv.name]
            # train-only state: the infer build must not clobber it (the
            # two traced fns are cached independently per mode)
            self._aux_update_names = aux_update_names

        group2dev = self._group2dev
        default_dev = self._ctx.jax_device() if group2dev else None

        def fn(arg_vals, aux_vals, keys):
            import jax
            env = {}
            for n in nodes:
                if n.op is None:
                    if n.attrs.get("__is_aux__"):
                        env[(id(n), 0)] = aux_vals[aux_order[n.name]]
                    else:
                        env[(id(n), 0)] = arg_vals[arg_order[n.name]]
                    continue
                op = get_op(n.op)
                attrs = {k: v for k, v in n.attrs.items()
                         if not k.startswith("__") and k != "ctx_group"}
                if op.mode_for(attrs):
                    attrs["_training"] = is_train
                if op.rng_for(attrs):
                    attrs["_rng_key"] = keys[rng_index[id(n)]]
                in_vals = [env[(id(inp), idx)] for (inp, idx) in n.inputs]
                if group2dev:
                    # cross-device copy onto this op's assigned device;
                    # ungrouped ops run on the bind context (AssignContext
                    # default-context behavior)
                    dev = group2dev.get(n.attrs.get("ctx_group"), default_dev)
                    in_vals = [jax.device_put(v, dev) for v in in_vals]
                out = op.fcompute(attrs, *in_vals)
                outs = out if isinstance(out, (tuple, list)) else [out]
                for i, o in enumerate(outs):
                    env[(id(n), i)] = o
            result = [env[(id(n), idx)] for (n, idx) in sym._entries]
            from .ops.nn_ops import BN_EPS_DEFAULT, bn_invstd_to_var
            for n in bn_nodes:
                m = float(n.attrs.get("momentum", 0.9))
                eps = float(n.attrs.get("eps", BN_EPS_DEFAULT))
                mean, invstd = env[(id(n), 1)], env[(id(n), 2)]
                # the op's third output is invstd (reference contract);
                # the running average tracks the raw variance
                var = bn_invstd_to_var(invstd, eps)
                old_mm = env[(id(n.inputs[3][0]), n.inputs[3][1])]
                old_mv = env[(id(n.inputs[4][0]), n.inputs[4][1])]
                result.append(old_mm * m + mean * (1 - m))
                result.append(old_mv * m + var * (1 - m))
            return result

        self._n_rng = len(rng_nodes)
        return fn

    def _keys(self):
        import jax
        from . import random as _random
        if self._n_rng == 0:
            import jax.numpy as jnp
            return jnp.zeros((1, 2), dtype=jnp.uint32)
        return jax.numpy.stack([_random.next_key() for _ in range(self._n_rng)])

    def forward(self, is_train=False, **kwargs):
        import jax
        for k, v in kwargs.items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(v._data if isinstance(v, NDArray)
                                           else jax.numpy.asarray(v))
        arg_vals = [self.arg_dict[n]._data for n in self.arg_names]
        aux_vals = [self.aux_dict[n]._data for n in self.aux_names]
        if is_train:
            if self._fwd_train is None:
                self._raw_train = self._fwd_train = self._build_fn(True)
            keys = self._keys()
            wrt_names = [n for n in self.arg_names
                         if self.grad_req.get(n, "null") != "null"]
            wrt_idx = [self.arg_names.index(n) for n in wrt_names]
            if self._group2dev:
                # per-op device placement needs eager dispatch, so the vjp
                # is built at forward time (re-traced per call — group2ctx
                # is a placement feature, not a throughput path)
                def f_wrt(*wrt_vals):
                    vals = list(arg_vals)
                    for i, v in zip(wrt_idx, wrt_vals):
                        vals[i] = v
                    return tuple(self._raw_train(vals, aux_vals, keys))

                outs, vjp = jax.vjp(f_wrt, *[arg_vals[i] for i in wrt_idx])
                self._vjp = (vjp, wrt_names)
            else:
                # compiled train path: jitted forward + separately-jitted
                # recompute backward, both cached on the executor — per-step
                # jax.vjp would re-trace the whole graph every iteration
                # (same defect class as CachedOp._get_bwd; see cached_op.py)
                if (self._jit_train_fwd is None
                        or self._jit_wrt != tuple(wrt_idx)):
                    raw = self._raw_train
                    idx = tuple(wrt_idx)
                    self._jit_train_fwd = jax.jit(
                        lambda a, x, k: tuple(raw(list(a), x, k)))

                    def bwd(a, x, k, cts):
                        def f_wrt(*wv):
                            vals = list(a)
                            for i, v in zip(idx, wv):
                                vals[i] = v
                            return tuple(raw(vals, x, k))
                        wv = [a[i] for i in idx]
                        return jax.vjp(f_wrt, *wv)[1](cts)
                    self._jit_train_bwd = jax.jit(bwd)
                    self._jit_wrt = idx
                outs = self._jit_train_fwd(tuple(arg_vals), tuple(aux_vals),
                                           keys)
                saved = (tuple(arg_vals), tuple(aux_vals), keys)
                bwd_fn = self._jit_train_bwd
                self._vjp = ((lambda cts: bwd_fn(*saved, cts)), wrt_names)
            # split off the appended BN moving-stat updates and fold them
            # into aux_dict (the pure-trace analog of the reference op's
            # in-place running-stat mutation)
            n_graph = len(outs) - len(self._aux_update_names)
            self._aux_tail = tuple(outs[n_graph:])
            for name, val in zip(self._aux_update_names, outs[n_graph:]):
                self.aux_dict[name]._set_data(val)
            outs = outs[:n_graph]
            self.outputs = [_wrap(o, ctx=self._ctx) for o in outs]
        else:
            if self._fwd_infer is None:
                raw = self._build_fn(False)
                # group2ctx placement needs eager dispatch: inside one jit,
                # XLA owns placement and per-op device pins are not honored
                self._fwd_infer = raw if self._group2dev else \
                    jax.jit(lambda a, x, k: tuple(raw(a, x, k)))
                self._raw_infer = raw
            keys = self._keys()
            outs = self._fwd_infer(arg_vals, aux_vals, keys)
            self.outputs = [_wrap(o, ctx=self._ctx) for o in outs]
        if self._monitor_callback is not None:
            for name, out in zip(self._symbol.list_outputs(), self.outputs):
                self._monitor_callback(name, out)
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        import jax.numpy as jnp
        if self._vjp is None:
            raise MXNetError("must call forward(is_train=True) before backward")
        vjp, wrt_names = self._vjp
        if out_grads is None:
            cts = tuple(jnp.ones_like(o._data) for o in self.outputs)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = tuple(g._data for g in out_grads)
        if self._group2dev:
            # head gradients must live where their outputs were produced —
            # the reverse pass then threads device_put transposes backwards
            import jax
            cts = tuple(jax.device_put(g, list(o._data.devices())[0])
                        for g, o in zip(cts, self.outputs))
        # the traced function also returned BN moving-stat updates; their
        # cotangents are zero (running stats are autograd.pause state)
        if getattr(self, "_aux_tail", ()):
            cts = cts + tuple(jnp.zeros_like(t) for t in self._aux_tail)
        grads = vjp(cts)
        for name, g in zip(wrt_names, grads):
            req = self.grad_req.get(name, "write")
            if req == "null":
                continue
            if name not in self.grad_dict or self.grad_dict[name] is None:
                self.grad_dict[name] = _wrap(g, ctx=self._ctx)
            elif req == "add":
                self.grad_dict[name]._set_data(self.grad_dict[name]._data + g)
            else:
                self.grad_dict[name]._set_data(g)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor for new input shapes (XLA recompiles per
        shape; the jit cache keeps previously-seen shapes hot — the analog of
        GraphExecutor::Reshape, graph_executor.cc:786)."""
        var_groups = self._symbol._variable_groups() if self._group2ctx else {}

        def alloc_ctx(name):
            group = var_groups.get(name)
            if self._group2ctx and group in self._group2ctx:
                return self._group2ctx[group]
            return self._ctx

        new_args = {}
        for n in self.arg_names:
            if n in kwargs:
                new_args[n] = nd_zeros(kwargs[n], ctx=alloc_ctx(n))
            else:
                new_args[n] = self.arg_dict[n]
        new_grads = None
        if self.grad_dict:
            new_grads = {n: nd_zeros(new_args[n].shape, ctx=alloc_ctx(n))
                         for n in self.grad_dict if self.grad_dict[n] is not None}
        return Executor(self._symbol, self._ctx, new_args, new_grads,
                        self.grad_req, dict(self.aux_dict),
                        group2ctx=self._group2ctx)

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, array in arg_params.items():
            if name in self.arg_dict:
                array.copyto(self.arg_dict[name])
            elif not allow_extra_params:
                raise ValueError("Find name \"%s\" that is not in the arguments" % name)
        if aux_params:
            for name, array in aux_params.items():
                if name in self.aux_dict:
                    array.copyto(self.aux_dict[name])
                elif not allow_extra_params:
                    raise ValueError("Find name \"%s\" that is not in the auxiliary "
                                     "states" % name)

    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor_callback = callback

    def debug_str(self):
        return "Executor(symbol=%s, args=%s)" % (self._symbol.name, self.arg_names)
