"""Profiler.

Reference: src/profiler/ + python/mxnet/profiler.py — engine-integrated op
profiling into chrome://tracing JSON (profiler.h:85-477, DumpProfile), aggregate
per-op stats table (aggregate_stats.cc), user Domain/Task/Counter/Marker objects
(profiler.py:198-283), env autostart MXNET_PROFILER_AUTOSTART.

TPU-native: wraps ``jax.profiler`` (XPlane/TensorBoard traces capture every XLA
op on-device — richer than the reference's per-engine-op events) and keeps the
reference's python surface: set_config/set_state/dump/dumps + Domain/Task/
Counter/Marker built on jax.profiler.TraceAnnotation.  The aggregate table is
produced from host-side event timings.
"""
from __future__ import annotations

import os
import time
import json
import threading
from collections import defaultdict

__all__ = ["set_config", "set_state", "state", "dump", "dumps", "merge_dumps",
           "pause", "resume", "memory_summary",
           "Domain", "Task", "Frame", "Event", "Counter", "Marker"]

_config = {"profile_all": False, "profile_symbolic": True, "profile_imperative": True,
           "profile_memory": False, "profile_api": False,
           "filename": "profile.json", "aggregate_stats": False}
_state = {"running": False, "trace_dir": None}
_events = []
_lock = threading.Lock()
_agg = defaultdict(lambda: [0, 0.0])  # name -> [count, total_ms]


def set_config(**kwargs):
    with _lock:
        _config.update(kwargs)


def set_state(state_="stop", profile_process="worker"):
    return _set_state(state_, fresh=True)


def _set_state(state_, fresh):
    import jax
    if state_ == "run":
        with _lock:
            if _state["running"]:
                return       # atomic check-and-claim: one starter wins
            _state["running"] = True
            if fresh:
                # each session is a fresh trace: without this, a long-lived
                # process that profiles periodically re-emits every prior
                # session's spans on dump() and grows the buffer unboundedly.
                # resume() passes fresh=False so a pause/resume cycle keeps
                # the pre-pause spans.  The per-op aggregate table resets
                # with the trace — otherwise dumps() mixes op stats across
                # sessions unless the caller remembered dumps(reset=True).
                _events.clear()
                _agg.clear()
            trace_dir = os.path.splitext(_config["filename"])[0] + "_xplane"
        # the jax call runs unlocked (it can block on backend init); the
        # claim above excludes a second start_trace, but a concurrent
        # stop() may land in this window — detected and honored below
        try:
            jax.profiler.start_trace(trace_dir)
        except Exception:
            trace_dir = None
        with _lock:
            if _state["running"]:
                _state["trace_dir"] = trace_dir
                trace_dir = None
        if trace_dir is not None:
            # a stop() interleaved before our trace existed and could not
            # stop it; honor the stop rather than leak an active trace
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
    elif state_ == "stop":
        with _lock:
            if not _state["running"]:
                return
            _state["running"] = False
            trace_dir = _state["trace_dir"]
            _state["trace_dir"] = None
        if trace_dir is not None:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass


def state():
    return "run" if _state["running"] else "stop"


def pause(profile_process="worker"):
    _set_state("stop", fresh=False)


def resume(profile_process="worker"):
    _set_state("run", fresh=False)


def profiling_imperative():
    """True when imperative op dispatch should be recorded — the gate the
    dispatch hot path checks (ProfileOperator's `IsProfiling` analog)."""
    return _state["running"] and _config.get("profile_imperative", True)


def profiling_active():
    """True while a profiling session is running.

    High-rate counter writers (the serving queue-depth / batch-latency
    gauges update on every request) must gate on this: Counter.set_value
    appends a trace event unconditionally, so an ungated per-request update
    in a long-lived server grows the event buffer without bound between
    dumps."""
    return _state["running"]


def record_op_span(name, t0_s, t1_s, cat="operator"):
    """One imperative op dispatch: B/E trace events + aggregate-table bump
    (src/profiler ProfileOperator analog).  Times are ``time.time()``
    seconds (the same timebase _record uses, so spans line up with
    Domain/Task events in the dumped trace) and measure host dispatch
    cost; device-side op timing is the XPlane trace captured alongside
    (see set_state)."""
    with _lock:
        for ph, ts in (("B", t0_s), ("E", t1_s)):
            _events.append({"name": name, "cat": cat, "ph": ph,
                            "ts": ts * 1e6, "pid": os.getpid(),
                            "tid": threading.get_ident(), "args": {}})
        a = _agg[name]
        a[0] += 1
        a[1] += (t1_s - t0_s) * 1e3


def _record(name, cat, ph, ts=None, args=None):
    with _lock:
        _events.append({"name": name, "cat": cat, "ph": ph,
                        "ts": (ts if ts is not None else time.time() * 1e6),
                        "pid": os.getpid(), "tid": threading.get_ident(),
                        "args": args or {}})


def dump(finished=True, profile_process="worker"):
    """Write accumulated host events as chrome://tracing JSON; device-side
    XPlane traces (if any) are in <filename>_xplane for TensorBoard.
    ``finished=True`` (the reference default) also retires the event
    buffer, so a later session starts clean."""
    with _lock:
        payload = {"traceEvents": list(_events)}
        if finished:
            _events.clear()
    with open(_config["filename"], "w") as f:
        json.dump(payload, f)


def dumps(reset=False):
    """Return the aggregate per-op stats table (aggregate_stats.cc analog)."""
    lines = ["%-40s %10s %14s %14s" % ("Name", "Calls", "Total(ms)", "Avg(ms)")]
    with _lock:
        for name, (cnt, total) in sorted(_agg.items(), key=lambda kv: -kv[1][1]):
            lines.append("%-40s %10d %14.3f %14.3f"
                         % (name, cnt, total, total / max(cnt, 1)))
        if reset:
            _agg.clear()
    return "\n".join(lines)


def memory_summary(device=None):
    """Live-allocation table: one row per (dtype, shape) bucket of the
    arrays currently alive on ``device`` (all devices if None), sorted by
    resident bytes — the storage-profiler analog (reference
    src/profiler/storage_profiler.h tags every Storage::Alloc with the
    requesting scope; here XLA owns allocation, so the observable unit is
    the live ``jax.Array`` population).

    Returns the formatted table; the last line totals bytes and count.
    Device-side internals (XLA scratch, donated aliasing) are invisible by
    design — for whole-HBM accounting use TensorBoard's memory_viewer on
    an XPlane trace from ``set_state('run')``/``dump()``."""
    import jax
    buckets = defaultdict(lambda: [0, 0])   # (dtype, shape) -> [count, bytes]
    total = n = 0
    for arr in jax.live_arrays():
        try:
            devs = getattr(arr, "devices", lambda: set())()
        except Exception:
            devs = set()
        if device is not None and devs and device not in devs:
            continue
        nbytes = arr.size * arr.dtype.itemsize
        key = (str(arr.dtype), tuple(arr.shape))
        buckets[key][0] += 1
        buckets[key][1] += nbytes
        total += nbytes
        n += 1
    lines = ["%-12s %-28s %8s %14s" % ("Dtype", "Shape", "Count", "Bytes")]
    for (dt, shp), (cnt, b) in sorted(buckets.items(),
                                      key=lambda kv: -kv[1][1]):
        lines.append("%-12s %-28s %8d %14d" % (dt, str(shp), cnt, b))
    lines.append("%-12s %-28s %8d %14d" % ("TOTAL", "", n, total))
    return "\n".join(lines)


def merge_dumps(filenames, out=None):
    """Aggregate per-op stats across several workers' trace dumps
    (the distributed analog of ``dumps()``; reference server-side profiling,
    include/mxnet/kvstore.h:49 SetServerProfilerCommand +
    tests/nightly/test_server_profiling.py).

    ``filenames``: per-rank chrome-trace JSON files written by ``dump()``.
    ``out``: optional path for the combined trace (events from all ranks in
    one timeline; pids distinguish the workers).  Returns the merged table.
    """
    events = []
    for fn in filenames:
        with open(fn) as f:
            events.extend(json.load(f).get("traceEvents", []))
    if out is not None:
        with open(out, "w") as f:
            json.dump({"traceEvents": events}, f)
    # pair B/E spans per (worker pid, thread, name) to recover durations
    open_spans = defaultdict(list)
    agg = defaultdict(lambda: [0, 0.0])
    for ev in sorted(events, key=lambda e: e.get("ts", 0)):
        name = ev.get("name")
        if name is None or ev.get("ph") not in ("B", "E"):
            # external tools emit name-less metadata ('M') events; skip
            # anything that isn't a named duration span
            continue
        key = (ev.get("pid"), ev.get("tid"), name)
        if ev.get("ph") == "B":
            open_spans[key].append(ev["ts"])
        elif open_spans[key]:
            begin = open_spans[key].pop()
            entry = agg[name]
            entry[0] += 1
            entry[1] += (ev["ts"] - begin) / 1e3
    lines = ["%-40s %10s %14s %14s" % ("Name", "Calls", "Total(ms)",
                                       "Avg(ms)")]
    for name, (cnt, total) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
        lines.append("%-40s %10d %14.3f %14.3f"
                     % (name, cnt, total, total / max(cnt, 1)))
    return "\n".join(lines)


class Domain:
    def __init__(self, name):
        self.name = name

    def __str__(self):
        return self.name

    def new_task(self, name):
        return Task(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Span:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name
        self._start = None
        self._annotation = None

    def start(self):
        import jax
        self._start = time.time()
        _record(self.name, str(self.domain), "B")
        try:
            self._annotation = jax.profiler.TraceAnnotation(self.name)
            self._annotation.__enter__()
        except Exception:
            self._annotation = None
        return self

    def stop(self):
        if self._annotation is not None:
            self._annotation.__exit__(None, None, None)
            self._annotation = None
        _record(self.name, str(self.domain), "E")
        if self._start is not None:
            with _lock:
                a = _agg[self.name]
                a[0] += 1
                a[1] += (time.time() - self._start) * 1e3
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()


class Task(_Span):
    pass


class Frame(_Span):
    pass


class Event(_Span):
    def __init__(self, name):
        super().__init__(Domain("event"), name)


class Counter:
    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self._value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self._value = value
        _record(self.name, str(self.domain), "C", args={"value": value})

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        _record(self.name, str(self.domain), "i", args={"s": scope[0]})


# env autostart (reference: MXNET_PROFILER_AUTOSTART, docs/faq/env_var.md:152
# — begin profiling at import so short scripts profile without code changes;
# registered in env.py).  jax.profiler.start_trace is deferred to the first
# set_state call's path, so a missing backend cannot break import.
if os.environ.get("MXNET_PROFILER_AUTOSTART", "0").lower() in ("1", "true"):
    try:
        set_state("run")
    except Exception:
        pass
