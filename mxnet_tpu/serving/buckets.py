"""Bucket ladder: the fixed shape menu the server compiles and serves from.

Reference design: the BucketingModule (mxnet_tpu/module/bucketing_module.py)
solves variable-shape *training* by keeping one executor per bucket key; this
is the serving-side analog.  XLA compiles one executable per input signature,
so an open-ended request mix would recompile forever — instead every model is
loaded with (1) an explicit list of admissible per-request input shapes and
(2) a batch-size ladder (1/2/4/.../max_batch by default).  Requests are only
coalesced with requests of the *same* input shape and the batch dimension is
padded up to the next rung, so steady-state traffic touches exactly
``len(shapes) x len(ladder)`` signatures — all of them precompiled by warmup.

Batch-dim padding keeps per-request outputs exact for batch-major models
(rows are independent in inference mode); feature-dim padding would not be —
that is the model's job (masking), so the server never does it.
"""
from __future__ import annotations

__all__ = ["BucketLadder", "shape_key", "normalize_shape_variants"]


class BucketLadder:
    """Sorted batch-size rungs; requests pad up to the smallest fitting rung.

    ``sizes`` overrides the default powers-of-two ladder (the e.g. 1/2/4/8
    sequence capped at ``max_batch``, with max_batch always a rung).
    """

    def __init__(self, max_batch=8, sizes=None):
        if sizes is None:
            sizes, b = [], 1
            while b < int(max_batch):
                sizes.append(b)
                b *= 2
            sizes.append(int(max_batch))
        self.sizes = sorted(set(int(s) for s in sizes))
        if not self.sizes or self.sizes[0] < 1:
            raise ValueError("bucket ladder needs positive sizes, got %r"
                             % (sizes,))
        self.max_batch = self.sizes[-1]

    def bucket(self, n):
        """Smallest rung >= n (callers never exceed max_batch per batch)."""
        for s in self.sizes:
            if s >= n:
                return s
        return self.max_batch

    def __iter__(self):
        return iter(self.sizes)

    def __len__(self):
        return len(self.sizes)

    def __repr__(self):
        return "BucketLadder(%s)" % (self.sizes,)


def shape_key(arrays):
    """Coalescing key of one request: per-input (shape, dtype) tuples."""
    return tuple((tuple(a.shape), str(a.dtype)) for a in arrays)


def normalize_shape_variants(input_shapes, n_inputs=None):
    """Normalize a user shape list to a list of per-input shape tuples.

    Each variant may be a plain shape tuple (single-input model) or a tuple
    of shape tuples (multi-input).  ``[(16,), (32,)]`` -> ``[((16,),),
    ((32,),)]``.
    """
    variants = []
    for spec in input_shapes:
        spec = tuple(spec)
        if spec and all(isinstance(s, int) for s in spec):
            spec = (spec,)                       # single-input shorthand
        else:
            spec = tuple(tuple(s) for s in spec)
        if n_inputs is not None and len(spec) != n_inputs:
            raise ValueError("shape variant %r has %d inputs, model takes %d"
                             % (spec, len(spec), n_inputs))
        variants.append(spec)
    if not variants:
        raise ValueError("input_shapes must list at least one shape variant")
    return variants
