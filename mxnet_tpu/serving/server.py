"""ModelServer: the in-process serving front-end.

The predictor API the MXNet paper names (Amalgamation/MXPred) ends at one
caller, one shape; this server is the production shape of that capability on
the TPU stack: multi-model, dynamically batched, deadline-aware, and
overload-safe, built entirely on ``CachedOp``'s compile cache.

Request lifecycle::

    predict() -> admission (shape check, bounded queue) -> micro-batcher
    coalesces same-shape requests -> padded batch on the bucket ladder ->
    one precompiled XLA executable -> per-row results fan back out

Every terminal state is a *status*, not an exception: TIMEOUT (deadline
passed before execution), OVERLOADED (queue full — shed at admission),
INVALID_INPUT (shape not in the model's bucket menu), ERROR (model raised).
Callers distinguish outcomes without try/except around the hot path, and an
overloaded server degrades to fast rejections instead of growing a queue.

Quickstart (see docs/SERVING.md)::

    server = serving.ModelServer()
    server.load_model("mlp", net, input_shapes=[(16,), (32,)], max_batch=8)
    res = server.predict("mlp", np.ones((16,), np.float32), timeout_ms=50)
    assert res.status == serving.OK
    server.stats()["models"]["mlp"]
    server.stop()
"""
from __future__ import annotations

import time

import numpy as np

from ..base import MXNetError
from .batcher import MicroBatcher, Request
from .registry import ModelRegistry, ServableModel

__all__ = ["ModelServer", "InferenceResult",
           "OK", "TIMEOUT", "OVERLOADED", "INVALID_INPUT", "ERROR"]

OK = "OK"
TIMEOUT = "TIMEOUT"
OVERLOADED = "OVERLOADED"
INVALID_INPUT = "INVALID_INPUT"
ERROR = "ERROR"

# extra client-side wait beyond the deadline before declaring TIMEOUT
# locally (covers worker wakeup jitter; the completion race is settled by
# Request.complete's first-wins lock either way)
_WAIT_GRACE_S = 0.25


class InferenceResult:
    """Terminal state of one request: status + outputs + latency."""

    __slots__ = ("status", "outputs", "latency_ms", "error")

    def __init__(self, status, outputs=None, latency_ms=None, error=None):
        self.status = status
        self.outputs = outputs
        self.latency_ms = latency_ms
        self.error = error

    @property
    def output(self):
        """First output array (the common single-output convenience)."""
        return self.outputs[0] if self.outputs else None

    def __repr__(self):
        return ("InferenceResult(status=%s, latency_ms=%s%s)"
                % (self.status,
                   None if self.latency_ms is None
                   else round(self.latency_ms, 3),
                   ", error=%r" % self.error if self.error else ""))


class _Entry:
    __slots__ = ("model", "batcher", "default_timeout_ms")

    def __init__(self, model, batcher, default_timeout_ms):
        self.model = model
        self.batcher = batcher
        self.default_timeout_ms = default_timeout_ms


class ModelServer:
    def __init__(self):
        self._registry = ModelRegistry()
        self._entries = {}           # name -> _Entry (guarded by registry)
        self._t_start = time.time()

    # -- model management ----------------------------------------------
    def load_model(self, name, block, input_shapes, dtype="float32",
                   max_batch=8, batch_ladder=None, max_queue=64,
                   linger_ms=2.0, default_timeout_ms=None, warmup=True,
                   flags=None):
        """Load a Gluon block (hybridizable or plain) for serving.

        ``input_shapes`` is the complete menu of admissible per-request
        shapes (batch dim excluded); requests outside it get
        INVALID_INPUT.  ``warmup=True`` precompiles every
        (shape, ladder rung) signature before the model takes traffic.
        Outputs must be batch-major (row i of every output belongs to
        request i) — true of standard inference-mode networks.
        """
        if name in self._entries:
            # cheap early duplicate check so a name collision fails before
            # the model build + whole-bucket-menu warmup compile; the
            # registry.add below is the authoritative (locked) check
            raise MXNetError("model %r is already loaded" % name)
        model = ServableModel(name, block, input_shapes, dtype=dtype,
                              max_batch=max_batch, batch_ladder=batch_ladder,
                              flags=flags)
        if warmup:
            model.warmup()
        self._registry.add(model)
        try:
            entry = _Entry(model, MicroBatcher(model, max_queue=max_queue,
                                               linger_ms=linger_ms),
                           default_timeout_ms)
            self._entries[name] = entry
        except Exception:
            self._registry.remove(name)
            raise
        return model

    def load_exported(self, name, prefix, epoch=0, input_names=("data",),
                      ctx=None, **kwargs):
        """Load an ``HybridBlock.export()`` artifact pair
        (``<prefix>-symbol.json`` + ``<prefix>-<epoch>.params``) via
        SymbolBlock.imports — the saved-model serving path."""
        from ..gluon import SymbolBlock
        block = SymbolBlock.imports(
            "%s-symbol.json" % prefix, list(input_names),
            "%s-%04d.params" % (prefix, epoch), ctx=ctx)
        return self.load_model(name, block, **kwargs)

    def unload(self, name):
        # registry first: concurrent predicts turn into unknown-model errors
        # for the whole teardown window (the reverse of load_model's order)
        self._registry.remove(name)
        entry = self._entries.pop(name)
        entry.batcher.stop()

    def models(self):
        return self._registry.names()

    def pause(self, name):
        """Stop dispatching ``name`` (maintenance/drain); admission stays
        open up to the queue bound."""
        self._entry(name).batcher.pause()

    def resume(self, name):
        self._entry(name).batcher.resume()

    # -- inference ------------------------------------------------------
    def predict_async(self, name, data, timeout_ms=None):
        """Submit one request; returns a Request handle (``wait()`` then
        read status/outputs) or an InferenceResult for immediate
        rejections (shed / invalid shape)."""
        entry = self._entry(name)
        model = entry.model
        try:
            inputs = self._coerce(model, data)
        except (ValueError, TypeError) as exc:
            # malformed payload (wrong input count, ragged/uncastable data)
            # is a status like every other terminal state, not an exception
            model.stats.on_invalid()
            return InferenceResult(INVALID_INPUT, latency_ms=0.0,
                                   error=str(exc))
        if not model.admissible(inputs):
            model.stats.on_invalid()
            return InferenceResult(
                INVALID_INPUT, latency_ms=0.0,
                error="shapes %s not in bucket menu %s"
                % ([tuple(a.shape) for a in inputs],
                   sorted(tuple(s for s, _ in k)
                          for k in model.allowed_keys)))
        if timeout_ms is None:
            timeout_ms = entry.default_timeout_ms
        deadline = (time.monotonic() + timeout_ms / 1e3
                    if timeout_ms is not None else None)
        request = Request(inputs, deadline=deadline)
        if not entry.batcher.submit(request):
            return InferenceResult(OVERLOADED, latency_ms=0.0,
                                   error="admission queue full")
        return request

    def predict(self, name, data, timeout_ms=None):
        """Blocking inference; always returns an InferenceResult."""
        handle = self.predict_async(name, data, timeout_ms=timeout_ms)
        if isinstance(handle, InferenceResult):
            return handle
        return self.result(name, handle)

    def result(self, name, request):
        """Wait a submitted Request out and convert it to a result."""
        entry = self._entry(name)
        if request.deadline is not None:
            request.wait(request.deadline - time.monotonic() + _WAIT_GRACE_S)
            # complete() is the atomic claim: if the worker's completion is
            # mid-flight (fields half-written under the lock) this blocks
            # until it finishes and then loses cleanly — an unlocked
            # `status is None` pre-check could pair our TIMEOUT with the
            # worker's outputs
            if request.complete(TIMEOUT):
                entry.model.stats.on_result(TIMEOUT, request.latency_ms)
        else:
            request.wait()
        status, outputs, latency_ms, error = request.snapshot()
        return InferenceResult(status, outputs, latency_ms, error)

    # -- observability --------------------------------------------------
    def stats(self):
        """Snapshot: per-model counters + compile-cache + warmup report."""
        models = {}
        for name in self._registry.names():
            model = self._registry.get(name)
            snap = model.stats.snapshot()
            cache = model.cache_stats()
            snap["cache"] = {"hits": cache["hits"],
                             "misses": cache["misses"],
                             "recompiles": cache["recompiles"],
                             "signatures": len(cache["signatures"])}
            snap["warmup"] = model.warmup_report
            models[name] = snap
        return {"uptime_s": time.time() - self._t_start, "models": models}

    # -- lifecycle ------------------------------------------------------
    def stop(self):
        for name in list(self._entries):
            self.unload(name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- internals ------------------------------------------------------
    def _entry(self, name):
        self._registry.get(name)       # raises the helpful unknown-model error
        entry = self._entries.get(name)
        if entry is None:
            # registry row exists but the entry doesn't: caller raced a
            # load/unload transition — a clean retryable error, not KeyError
            raise MXNetError("model %r is mid load/unload; retry" % name)
        return entry

    @staticmethod
    def _coerce(model, data):
        """Normalize user data (array / NDArray / tuple) to the model's
        per-input numpy arrays with the configured dtypes."""
        from ..ndarray import NDArray
        if isinstance(data, (list, tuple)):
            items = list(data)
        else:
            items = [data]
        if len(items) != model.n_inputs:
            raise ValueError("model %r takes %d input(s), got %d"
                             % (model.name, model.n_inputs, len(items)))
        out = []
        for x, dt in zip(items, model.dtypes):
            if isinstance(x, NDArray):
                x = x.asnumpy()
            out.append(np.asarray(x, dtype=dt))
        return tuple(out)
