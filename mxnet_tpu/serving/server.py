"""ModelServer: the in-process serving front-end.

The predictor API the MXNet paper names (Amalgamation/MXPred) ends at one
caller, one shape; this server is the production shape of that capability on
the TPU stack: multi-model, dynamically batched, deadline-aware, and
overload-safe, built entirely on ``CachedOp``'s compile cache.

Request lifecycle::

    predict() -> admission (shape check, bounded queue) -> micro-batcher
    coalesces same-shape requests -> padded batch on the bucket ladder ->
    one precompiled XLA executable -> per-row results fan back out

Every terminal state is a *status*, not an exception: TIMEOUT (deadline
passed before execution), OVERLOADED (queue full — shed at admission),
INVALID_INPUT (shape not in the model's bucket menu), ERROR (model raised),
UNAVAILABLE (retryable: circuit breaker open, or the server/model is
shutting down).  Callers distinguish outcomes without try/except around the
hot path, and an overloaded server degrades to fast rejections instead of
growing a queue.

Self-healing (docs/ROBUSTNESS.md): each model carries a circuit breaker
(serving/health.py).  After K consecutive batch failures the breaker opens
and admission fast-fails with UNAVAILABLE — no queueing, no XLA call — then
half-open probing with exponential backoff recovers the model the moment
its backend comes back.  ``stats()`` exposes per-model ``health``
(HEALTHY/DEGRADED/UNAVAILABLE) and the breaker counters.

Quickstart (see docs/SERVING.md)::

    server = serving.ModelServer()
    server.load_model("mlp", net, input_shapes=[(16,), (32,)], max_batch=8)
    res = server.predict("mlp", np.ones((16,), np.float32), timeout_ms=50)
    assert res.status == serving.OK
    server.stats()["models"]["mlp"]
    server.stop()
"""
from __future__ import annotations

import time

import numpy as np

from ..base import MXNetError
from .batcher import MicroBatcher, Request
from .health import PROBE, REJECT
from .registry import ModelRegistry, ServableModel

__all__ = ["ModelServer", "InferenceResult",
           "OK", "TIMEOUT", "OVERLOADED", "INVALID_INPUT", "ERROR",
           "UNAVAILABLE"]

OK = "OK"
TIMEOUT = "TIMEOUT"
OVERLOADED = "OVERLOADED"
INVALID_INPUT = "INVALID_INPUT"
ERROR = "ERROR"
# retryable terminal state: breaker open or server/model shutting down —
# the caller should back off and try again (or another replica), unlike
# ERROR which means THIS request's execution failed
UNAVAILABLE = "UNAVAILABLE"

# extra client-side wait beyond the deadline before declaring TIMEOUT
# locally (covers worker wakeup jitter; the completion race is settled by
# Request.complete's first-wins lock either way)
_WAIT_GRACE_S = 0.25
# how long result() waits on a deadline-less request whose model is being
# torn down before claiming UNAVAILABLE itself: must exceed the batcher's
# stop() join timeout (5 s) so the drain normally wins the claim
_TEARDOWN_WAIT_S = 6.0


class InferenceResult:
    """Terminal state of one request: status + outputs + latency."""

    __slots__ = ("status", "outputs", "latency_ms", "error")

    def __init__(self, status, outputs=None, latency_ms=None, error=None):
        self.status = status
        self.outputs = outputs
        self.latency_ms = latency_ms
        self.error = error

    @property
    def output(self):
        """First output array (the common single-output convenience)."""
        return self.outputs[0] if self.outputs else None

    def __repr__(self):
        return ("InferenceResult(status=%s, latency_ms=%s%s)"
                % (self.status,
                   None if self.latency_ms is None
                   else round(self.latency_ms, 3),
                   ", error=%r" % self.error if self.error else ""))


class _Entry:
    __slots__ = ("model", "batcher", "default_timeout_ms")

    def __init__(self, model, batcher, default_timeout_ms):
        self.model = model
        self.batcher = batcher
        self.default_timeout_ms = default_timeout_ms


class ModelServer:
    def __init__(self):
        import threading
        self._registry = ModelRegistry()
        self._t_start = time.time()
        self._lifecycle_lock = threading.Lock()
        # guarded by _lifecycle_lock: name -> _Entry map, the closed flag,
        # and the set of names that were EVER loaded (so result() can tell
        # "model torn down mid-flight" from a caller's typo'd name)
        self._entries = {}
        self._closed = False
        self._ever_loaded = set()
        # attached decode engines (attach_engine): name -> engine, also
        # guarded by _lifecycle_lock; they report through stats()/health()
        # beside the batched models
        self._engines = {}

    def _is_closed(self):
        with self._lifecycle_lock:
            return self._closed

    # -- model management ----------------------------------------------
    def load_model(self, name, block, input_shapes, dtype="float32",
                   max_batch=8, batch_ladder=None, max_queue=64,
                   linger_ms=2.0, default_timeout_ms=None, warmup=True,
                   flags=None, breaker_threshold=5, breaker_backoff_ms=50.0,
                   breaker_max_backoff_ms=2000.0, generation=None):
        """Load a Gluon block (hybridizable or plain) for serving.

        ``input_shapes`` is the complete menu of admissible per-request
        shapes (batch dim excluded); requests outside it get
        INVALID_INPUT.  ``warmup=True`` precompiles every
        (shape, ladder rung) signature before the model takes traffic.
        Outputs must be batch-major (row i of every output belongs to
        request i) — true of standard inference-mode networks.
        """
        with self._lifecycle_lock:
            if self._closed:
                raise MXNetError("server is stopped; create a new "
                                 "ModelServer")
            duplicate = name in self._entries
            engine_clash = name in self._engines
        if engine_clash:
            # models and engines share one health/stats namespace
            raise MXNetError("name %r is already an attached engine" % name)
        if duplicate:
            # cheap early duplicate check so a name collision fails before
            # the model build + whole-bucket-menu warmup compile; the
            # registry.add below is the authoritative (locked) check
            raise MXNetError("model %r is already loaded" % name)
        model = ServableModel(name, block, input_shapes, dtype=dtype,
                              max_batch=max_batch, batch_ladder=batch_ladder,
                              flags=flags, breaker_threshold=breaker_threshold,
                              breaker_backoff_ms=breaker_backoff_ms,
                              breaker_max_backoff_ms=breaker_max_backoff_ms,
                              generation=generation)
        if warmup:
            model.warmup()
        self._registry.add(model)
        entry = None
        try:
            entry = _Entry(model, MicroBatcher(model, max_queue=max_queue,
                                               linger_ms=linger_ms),
                           default_timeout_ms)
            # final registration re-checks closed under the lifecycle lock:
            # a stop() that raced the (slow) build + warmup above must not
            # end up with a live batcher thread on a stopped server
            with self._lifecycle_lock:
                if self._closed:
                    raise MXNetError("server stopped while loading %r"
                                     % name)
                self._entries[name] = entry
                self._ever_loaded.add(name)
        except Exception:
            self._registry.remove(name)
            if entry is not None:
                entry.batcher.stop()
            raise
        return model

    def load_exported(self, name, prefix, epoch=0, input_names=("data",),
                      ctx=None, **kwargs):
        """Load an ``HybridBlock.export()`` artifact pair
        (``<prefix>-symbol.json`` + ``<prefix>-<epoch>.params``) via
        SymbolBlock.imports — the saved-model serving path."""
        from ..gluon import SymbolBlock
        block = SymbolBlock.imports(
            "%s-symbol.json" % prefix, list(input_names),
            "%s-%04d.params" % (prefix, epoch), ctx=ctx)
        return self.load_model(name, block, **kwargs)

    def unload(self, name):
        # registry first: concurrent predicts turn into unknown-model errors
        # for the whole teardown window (the reverse of load_model's order)
        self._registry.remove(name)
        with self._lifecycle_lock:
            entry = self._entries.pop(name)
        entry.batcher.stop()

    def models(self):
        return self._registry.names()

    def pause(self, name):
        """Stop dispatching ``name`` (maintenance/drain); admission stays
        open up to the queue bound."""
        self._entry(name).batcher.pause()

    def resume(self, name):
        self._entry(name).batcher.resume()

    # -- decode engines ---------------------------------------------------
    def attach_engine(self, engine):
        """Register a decode engine (serving/decode) on this server's
        observability surface, under its ``engine.name``.

        The engine keeps its own request API and worker thread; attaching
        makes its DecodeStats/breaker report through the same
        ``stats()``/``health()`` surface a fleet router reads for batched
        models, and ``stop()`` tears it down with the rest of the server.
        Names are one namespace: an engine cannot shadow a loaded model."""
        name = engine.name
        with self._lifecycle_lock:
            if self._closed:
                raise MXNetError("server is stopped; create a new "
                                 "ModelServer")
            if name in self._engines:
                raise MXNetError("engine %r is already attached" % name)
            if name in self._entries:
                raise MXNetError("name %r is already a loaded model" % name)
            self._engines[name] = engine
        return engine

    def detach_engine(self, name):
        """Unregister (but do NOT stop) an attached engine; returns it."""
        with self._lifecycle_lock:
            try:
                return self._engines.pop(name)
            except KeyError:
                raise MXNetError("no engine %r attached; attached: %s"
                                 % (name, sorted(self._engines) or "none"))

    def engines(self):
        with self._lifecycle_lock:
            return sorted(self._engines)

    # -- inference ------------------------------------------------------
    def predict_async(self, name, data, timeout_ms=None):
        """Submit one request; returns a Request handle (``wait()`` then
        read status/outputs) or an InferenceResult for immediate
        rejections (shed / invalid shape / breaker open / shutting down)."""
        if self._is_closed():
            # a closed server is a lifecycle condition, not a caller error:
            # clean retryable status instead of raising at every call site
            return InferenceResult(UNAVAILABLE, latency_ms=0.0,
                                   error="server stopped")
        try:
            entry = self._entry(name)
        except MXNetError:
            if self._is_closed() or name in self._registry.names():
                # closing, or caught mid load/unload transition
                return InferenceResult(UNAVAILABLE, latency_ms=0.0,
                                       error="model %r is mid load/unload "
                                             "or shutting down; retry" % name)
            raise   # genuinely unknown model: keep the helpful error
        model = entry.model
        try:
            inputs = self._coerce(model, data)
        except (ValueError, TypeError) as exc:
            # malformed payload (wrong input count, ragged/uncastable data)
            # is a status like every other terminal state, not an exception
            model.stats.on_invalid()
            return InferenceResult(INVALID_INPUT, latency_ms=0.0,
                                   error=str(exc))
        if not model.admissible(inputs):
            model.stats.on_invalid()
            return InferenceResult(
                INVALID_INPUT, latency_ms=0.0,
                error="shapes %s not in bucket menu %s"
                % ([tuple(a.shape) for a in inputs],
                   sorted(tuple(s for s, _ in k)
                          for k in model.allowed_keys)))
        # breaker admission runs AFTER validation, immediately before the
        # queue: a request that can never execute (invalid shape, malformed
        # payload) must not consume the half-open probe slot, or junk
        # traffic could starve recovery indefinitely
        decision = model.breaker.admit()
        if decision == REJECT:
            # fast retryable rejection: the breaker is open — no queueing,
            # no batcher wakeup, no XLA call (the self-healing fast path)
            model.stats.on_unavailable(rejected=True)
            snap = model.breaker.snapshot()
            return InferenceResult(
                UNAVAILABLE, latency_ms=0.0,
                error="circuit open after %d consecutive failure(s); "
                      "retry in <= %.0f ms"
                      % (snap["consecutive_failures"],
                         snap["backoff_s"] * 1e3))
        if timeout_ms is None:
            timeout_ms = entry.default_timeout_ms
        deadline = (time.monotonic() + timeout_ms / 1e3
                    if timeout_ms is not None else None)
        request = Request(inputs, deadline=deadline, stats=model.stats)
        admitted = entry.batcher.submit(request)
        if admitted is not True:
            if decision == PROBE:
                # THIS request held the half-open probe slot and never
                # reached the worker: hand the slot back (releasing
                # unconditionally could cancel another request's live probe
                # window and break the single-probe invariant)
                model.breaker.release_probe()
            if admitted == "stopping":
                # the batcher itself reports WHY it refused, so exactly one
                # outcome is counted: a shutdown refusal is UNAVAILABLE
                # (counted here), a full queue already counted its shed
                model.stats.on_unavailable(rejected=True)
                return InferenceResult(UNAVAILABLE, latency_ms=0.0,
                                       error="server shutting down")
            return InferenceResult(OVERLOADED, latency_ms=0.0,
                                   error="admission queue full")
        return request

    def predict(self, name, data, timeout_ms=None):
        """Blocking inference; always returns an InferenceResult."""
        handle = self.predict_async(name, data, timeout_ms=timeout_ms)
        if isinstance(handle, InferenceResult):
            return handle
        return self.result(name, handle)

    def result(self, name, request):
        """Wait a submitted Request out and convert it to a result.

        Safe against teardown races: if the model was unloaded (or the
        server stopped) while the request was in flight, the batcher's
        stop() has completed — or is about to complete — every queued
        request with UNAVAILABLE, so this never hangs on a dead queue and
        never raises KeyError; worst case it claims UNAVAILABLE itself
        after a bounded wait, counting the terminal through the stats
        handle the request carries (conservation survives teardown).  A
        name that was NEVER loaded still raises the unknown-model error —
        a typo must not clobber a live request on a healthy server."""
        try:
            entry = self._entry(name)
        except MXNetError:
            with self._lifecycle_lock:
                known = name in self._ever_loaded
            if not known and not self._is_closed():
                raise
            entry = None   # unloaded/closing mid-flight; see docstring
        stats = entry.model.stats if entry is not None else request.stats
        if request.deadline is not None:
            request.wait(request.deadline - time.monotonic() + _WAIT_GRACE_S)
            # complete() is the atomic claim: if the worker's completion is
            # mid-flight (fields half-written under the lock) this blocks
            # until it finishes and then loses cleanly — an unlocked
            # `status is None` pre-check could pair our TIMEOUT with the
            # worker's outputs
            if request.complete(TIMEOUT):
                if stats is not None:
                    stats.on_result(TIMEOUT, request.latency_ms)
        elif entry is not None:
            request.wait()
        else:
            # no deadline and the model is gone: the teardown drain
            # completes every queued request, but its batcher join can
            # take up to its 5 s timeout with a wedged batch — wait that
            # out before claiming UNAVAILABLE ourselves (counted through
            # the carried stats so the admitted request still reaches
            # exactly one terminal counter)
            if not request.wait(_TEARDOWN_WAIT_S):
                if request.complete(UNAVAILABLE,
                                    error="server shutting down"):
                    if stats is not None:
                        stats.on_result(UNAVAILABLE, request.latency_ms)
        status, outputs, latency_ms, error = request.snapshot()
        return InferenceResult(status, outputs, latency_ms, error)

    # -- observability --------------------------------------------------
    def stats(self):
        """Snapshot: per-model counters + compile-cache + warmup report +
        health/breaker state (health.py), plus one ``engines`` section per
        attached decode engine (its full DecodeStats snapshot) so decode
        traffic reports through the same surface."""
        models = {}
        for name in self._registry.names():
            try:
                model = self._registry.get(name)
            except MXNetError:
                continue   # unloaded between names() and get()
            snap = model.stats.snapshot()
            cache = model.cache_stats()
            snap["cache"] = {"hits": cache["hits"],
                             "misses": cache["misses"],
                             "recompiles": cache["recompiles"],
                             "signatures": len(cache["signatures"])}
            snap["warmup"] = model.warmup_report
            snap["health"] = model.breaker.health()
            snap["breaker"] = model.breaker.snapshot()
            # convenience alias; the breaker snapshot is the single source
            snap["breaker_opens"] = snap["breaker"]["opens"]
            models[name] = snap
        with self._lifecycle_lock:
            engines = dict(self._engines)
        engine_snaps = {name: eng.stats_snapshot()
                        for name, eng in engines.items()}
        return {"uptime_s": time.time() - self._t_start, "models": models,
                "engines": engine_snaps}

    def health(self, name):
        """HEALTHY / DEGRADED / UNAVAILABLE for one model or attached
        engine (models and engines share the name namespace)."""
        try:
            return self._entry(name).model.breaker.health()
        except MXNetError:
            with self._lifecycle_lock:
                engine = self._engines.get(name)
            if engine is not None:
                return engine.health()
            raise

    # -- lifecycle ------------------------------------------------------
    def stop(self):
        with self._lifecycle_lock:
            self._closed = True
            names = list(self._entries)
            engines = [self._engines.pop(n) for n in list(self._engines)]
        for name in names:
            self.unload(name)
        for engine in engines:
            engine.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- internals ------------------------------------------------------
    def _entry(self, name):
        self._registry.get(name)       # raises the helpful unknown-model error
        with self._lifecycle_lock:
            entry = self._entries.get(name)
        if entry is None:
            # registry row exists but the entry doesn't: caller raced a
            # load/unload transition — a clean retryable error, not KeyError
            raise MXNetError("model %r is mid load/unload; retry" % name)
        return entry

    @staticmethod
    def _coerce(model, data):
        """Normalize user data (array / NDArray / tuple) to the model's
        per-input numpy arrays with the configured dtypes."""
        from ..ndarray import NDArray
        if isinstance(data, (list, tuple)):
            items = list(data)
        else:
            items = [data]
        if len(items) != model.n_inputs:
            raise ValueError("model %r takes %d input(s), got %d"
                             % (model.name, model.n_inputs, len(items)))
        out = []
        for x, dt in zip(items, model.dtypes):
            if isinstance(x, NDArray):
                x = x.asnumpy()  # mxflow: sync-ok(request admission: device handles coerce to host rows once)
            out.append(np.asarray(x, dtype=dt))
        return tuple(out)
