"""mxnet_tpu.serving — in-process model server on the CachedOp compile cache.

The serving subsystem the north star names: a multi-model, dynamically
micro-batched inference server with a fixed bucket ladder (so steady-state
traffic never triggers a fresh XLA compile), per-request deadlines, bounded
admission with load-shedding backpressure, and profiler-integrated
observability.  See docs/SERVING.md for architecture and tuning.

    from mxnet_tpu import serving
    server = serving.ModelServer()
    server.load_model("net", block, input_shapes=[(16,), (32,)])
    result = server.predict("net", x, timeout_ms=100)
"""
from .buckets import BucketLadder, shape_key
from .batcher import MicroBatcher, Request
from .health import CircuitBreaker, HEALTHY, DEGRADED
from .registry import ModelRegistry, ServableModel
from .server import (ModelServer, InferenceResult,
                     OK, TIMEOUT, OVERLOADED, INVALID_INPUT, ERROR,
                     UNAVAILABLE)
from .fleet import FleetRouter, FleetStats, DecodeFleetStats
from . import decode
from . import deploy
from . import disagg
from . import traffic
from .deploy import DeploymentController

__all__ = ["ModelServer", "InferenceResult", "BucketLadder", "Request",
           "MicroBatcher", "ModelRegistry", "ServableModel", "shape_key",
           "CircuitBreaker", "HEALTHY", "DEGRADED", "decode", "deploy",
           "disagg", "traffic", "DeploymentController",
           "FleetRouter", "FleetStats", "DecodeFleetStats",
           "OK", "TIMEOUT", "OVERLOADED", "INVALID_INPUT", "ERROR",
           "UNAVAILABLE"]
