"""SLO-driven per-tier autoscaling for the disaggregated topology.

``FleetRouter.scaling_advice()`` has always *described* what a policy
should do; this module closes the loop and does it.  An
:class:`Autoscaler` polls both tiers of a :class:`~.router.DisaggRouter`
and, per tier, compares live signals against a :class:`TierPolicy`:

* **SLO tail latency** — p99 TTFT (prefill's product) and p99 TPOT
  (decode's product), both read from the prefill router's end-to-end
  ``decode_stats`` ledger (the single terminal hook means only that
  ledger sees finished streams);
* **headroom** — the tier's own ``scaling_advice()`` KV utilization and
  queue fill.

A breach of either scales the tier OUT: ``add_replica()`` joins a bare
replica, then ``scale_decode()`` raises the engine target so the
rebalancer builds AND warms the new engine before its placement commits
(warm-before-cutover — a joining replica never serves cold).  A
sustained-idle tier (no SLO breach, KV and queue under the low-water
marks) scales IN: the target drops first (so the rebalancer cannot
re-place onto survivors), then the victim is drained — every in-flight
stream hands off to a survivor via the fenced export/import protocol —
and retired with ``remove_replica()``.  One action per tier per poll,
bounded by ``min_replicas``/``max_replicas`` and a per-tier cooldown so
a burst cannot thrash the fleet.

Every poll lands the decision on the profiler timeline (gated on
``profiling_active()``, like all serving counters): ``<tier>:replicas``,
``<tier>:slo_p99_ttft_ms``, ``<tier>:slo_p99_tpot_ms`` — a trace dump
shows replica counts stepping against the tail latencies that drove
them.

The ``disagg`` mxstress scenario and tests/test_disagg.py exercise both
directions live under chaos; docs/ROBUSTNESS.md ("Autoscaler
drain/kill semantics") documents the failure contract.
"""
from __future__ import annotations

import threading
import time

from ... import profiler

__all__ = ["Autoscaler", "TierPolicy"]


class TierPolicy:
    """Scaling targets for one tier.

    ``slo_p99_ttft_ms`` / ``slo_p99_tpot_ms``: tail-latency ceilings
    (None = unchecked; prefill typically sets TTFT, decode sets TPOT).
    ``kv_high``/``queue_high``: headroom breach thresholds (scale out);
    ``kv_low``/``queue_low``: idle thresholds (scale in, only when no
    SLO is breached).  ``cooldown_s`` spaces actions on the same tier.
    """

    def __init__(self, min_replicas=1, max_replicas=8,
                 slo_p99_ttft_ms=None, slo_p99_tpot_ms=None,
                 kv_high=0.85, kv_low=0.15,
                 queue_high=0.85, queue_low=0.15, cooldown_s=0.0):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if not 0.0 <= kv_low < kv_high <= 1.0:
            raise ValueError("need 0 <= kv_low < kv_high <= 1")
        if not 0.0 <= queue_low < queue_high <= 1.0:
            raise ValueError("need 0 <= queue_low < queue_high <= 1")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.slo_p99_ttft_ms = slo_p99_ttft_ms
        self.slo_p99_tpot_ms = slo_p99_tpot_ms
        self.kv_high = float(kv_high)
        self.kv_low = float(kv_low)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.cooldown_s = float(cooldown_s)


class Autoscaler:
    """Drive both tiers of a :class:`~.router.DisaggRouter` toward
    their :class:`TierPolicy` targets.  Call :meth:`poll` on whatever
    cadence the deployment likes (tests call it directly); each call
    evaluates both tiers and performs at most one scaling action per
    tier.  Not re-entrant: serialize polls (one ``_lock`` enforces
    it)."""

    TIERS = ("prefill", "decode")

    def __init__(self, disagg, prefill=None, decode=None):
        self.disagg = disagg
        self.policies = {"prefill": prefill or TierPolicy(),
                         "decode": decode or TierPolicy()}
        self._lock = threading.Lock()
        self._last_action = {t: None for t in self.TIERS}
        self.decisions = []   # every non-hold action, in order
        domain = profiler.Domain("serving")
        self._counters = {
            t: {"replicas": domain.new_counter("%s:replicas" % t),
                "ttft": domain.new_counter("%s:slo_p99_ttft_ms" % t),
                "tpot": domain.new_counter("%s:slo_p99_tpot_ms" % t)}
            for t in self.TIERS}

    # -- signal plumbing --------------------------------------------------
    def _live(self, router):
        return sorted(rid for rid, st in router.replicas().items()
                      if st == "LIVE")

    def _victim(self, router):
        """Scale-in victim: the highest-numbered LIVE replica hosting a
        decode engine (deterministic, and the last to have joined under
        the rid scheme — survivors keep the longest-warmed copies)."""
        placed = set()
        for name in router.decode_models():
            placed.update(router.stats()["decode_models"][name]["placement"])
        live = [rid for rid in self._live(router) if rid in placed]
        if not live:
            return None
        return max(live, key=lambda rid: int(rid.lstrip("r")))

    # -- the loop body ----------------------------------------------------
    def poll(self):
        """Evaluate both tiers; returns ``{tier: decision}`` where each
        decision carries the action taken (``scale_out``/``scale_in``/
        ``hold``), the replica count after it, the signals read, and the
        reasons."""
        with self._lock:
            slo = self.disagg.prefill.decode_stats.snapshot()
            p99_ttft = slo["ttft_ms"]["p99"]
            p99_tpot = slo["tpot_ms"]["p99"]
            out = {}
            for tier in self.TIERS:
                out[tier] = self._poll_tier(tier, p99_ttft, p99_tpot)
            return out

    def _poll_tier(self, tier, p99_ttft, p99_tpot):
        router = getattr(self.disagg, tier)
        pol = self.policies[tier]
        advice = router.scaling_advice()
        kv = advice["kv_utilization"]
        queue = advice["queue_fill"]
        live = self._live(router)
        n = len(live)
        reasons = []
        if pol.slo_p99_ttft_ms is not None and p99_ttft > pol.slo_p99_ttft_ms:
            reasons.append("p99 TTFT %.1fms > SLO %.1fms"
                           % (p99_ttft, pol.slo_p99_ttft_ms))
        if pol.slo_p99_tpot_ms is not None and p99_tpot > pol.slo_p99_tpot_ms:
            reasons.append("p99 TPOT %.1fms > SLO %.1fms"
                           % (p99_tpot, pol.slo_p99_tpot_ms))
        if kv >= pol.kv_high:
            reasons.append("kv utilization %.2f >= %.2f" % (kv, pol.kv_high))
        if queue >= pol.queue_high:
            reasons.append("queue fill %.2f >= %.2f"
                           % (queue, pol.queue_high))
        action = "hold"
        if reasons:
            if n >= pol.max_replicas:
                reasons.append("at max_replicas %d" % pol.max_replicas)
            elif self._cooling(tier, pol):
                reasons.append("in cooldown")
            else:
                action = "scale_out"
        elif kv <= pol.kv_low and queue <= pol.queue_low \
                and n > pol.min_replicas and not self._cooling(tier, pol):
            action = "scale_in"
            reasons = ["idle: kv %.2f <= %.2f, queue %.2f <= %.2f"
                       % (kv, pol.kv_low, queue, pol.queue_low)]
        if action == "scale_out":
            n = self._scale_out(router, n)
        elif action == "scale_in":
            n = self._scale_in(router, n)
        decision = {"action": action, "replicas": n, "reasons": reasons,
                    "kv_utilization": kv, "queue_fill": queue,
                    "p99_ttft_ms": p99_ttft, "p99_tpot_ms": p99_tpot}
        if action != "hold":
            self._last_action[tier] = time.monotonic()
            self.decisions.append(dict(decision, tier=tier))
        if profiler.profiling_active():
            c = self._counters[tier]
            c["replicas"].set_value(n)
            c["ttft"].set_value(p99_ttft)
            c["tpot"].set_value(p99_tpot)
        return decision

    def _cooling(self, tier, pol):
        last = self._last_action[tier]
        return (last is not None
                and time.monotonic() - last < pol.cooldown_s)

    def _scale_out(self, router, n):
        """Join a bare replica, then raise every engine target so the
        rebalancer builds + warms onto it before placement commits."""
        router.add_replica()
        for name in router.decode_models():
            router.scale_decode(name, n + 1)
        return n + 1

    def _scale_in(self, router, n):
        """Lower every engine target FIRST (the rebalancer must not
        re-place onto survivors), then drain the victim — its streams
        hand off via the fenced export/import protocol — and retire
        it."""
        victim = self._victim(router)
        if victim is None:
            return n
        for name in router.decode_models():
            router.scale_decode(name, max(1, n - 1))
        router.drain(victim)
        router.remove_replica(victim)
        return n - 1
