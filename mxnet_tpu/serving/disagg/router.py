"""Disaggregated prefill/decode topology: two fleets, one stream.

Prefill is compute-bound (one big batched matmul over the prompt) and
decode is memory-bandwidth-bound (one token per step, the whole KV
resident); co-locating them makes each steal the other's latency
budget — a long prefill stalls every decode step behind it, and decode
occupancy starves prefill of compute.  :class:`DisaggRouter` splits the
roles across two independent :class:`~mxnet_tpu.serving.fleet.FleetRouter`
tiers:

* the **prefill tier** runs chunked-prefill-only engines
  (``DecodeEngine(prefill_only=True)``): each stream is admitted here,
  prefills in chunks, emits its first token (TTFT), and is immediately
  handed off;
* the **decode tier** owns the stream from token two to its terminal:
  the handoff carries the prompt's K/V pages, the sampler state (seed +
  draws burned), and the cursor — the exact ``export_stream`` snapshot
  shape — and lands via ``FleetRouter.adopt_stream``, which re-owns the
  stream to the target replica's ``(rid, generation)`` fencing token
  BEFORE importing, so the prefill incarnation can never emit past the
  handoff point.

Conservation across the boundary stays on ONE ledger: the prefill
router admits every stream and holds the single ``on_terminal`` hook,
so its ``decode_stats`` settles ``requests == ok + timeouts + errors +
unavailable`` for the whole pipeline regardless of which tier produced
the terminal.  ``mark_departed`` detaches the stream's replica pin the
moment it leaves the prefill tier (a later prefill-replica death must
not fence a stream that now lives elsewhere), and a failed adoption —
decode tier full, draining, or gone — terminates the stream UNAVAILABLE
with its one-token prefix intact for re-admission.

Handoff-at-first-token state machine (docs/SERVING.md "Disaggregated
prefill/decode" has the full walk-through)::

    prefill worker            DisaggRouter              decode tier
    --------------            ------------              -----------
    final chunk done
    emit token 1 (TTFT)
    snapshot K/V+sampler
    free local blocks
    sink(stream, snap) ──────> mark_departed(stream)
                               adopt_stream ──────────> check_generation
                                                        set_owner((rid2,g2))
                                                        import_stream
                               record handoff_ms
    handed_off += 1   <─────── True
                                                        decode to terminal
                                                        (one complete(),
                                                         prefill router's
                                                         terminal hook
                                                         settles counters)

Locking: the router itself holds no lock — every mutable piece lives in
the two tier routers (each with its own ``_lock`` discipline) or in
:class:`DisaggStats` (one ``threading.Lock``).  The handoff sink runs on
a prefill engine worker thread and calls only lock-safe tier-router
entry points, never an engine on the prefill tier.
"""
from __future__ import annotations

import threading
import time

from ... import profiler
from ...base import MXNetError
from ..fleet import FleetRouter
from ..stats import LatencyWindow

__all__ = ["DisaggRouter", "DisaggStats"]


class DisaggStats:
    """Cross-tier handoff counters + latency window.  Thread-safe.

    ``handoffs`` counts streams that found a decode home; ``failures``
    counts streams the decode tier could not adopt (they terminate
    UNAVAILABLE, prefix intact).  ``handoff_ms`` measures the sink's
    wall time — detach, adopt, import — which is dead air between the
    first token and the second, so it sits directly on TPOT.  The same
    number lands on the profiler timeline as the ``prefill:handoff_ms``
    Counter (gated on ``profiling_active()``, like every serving
    counter)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.handoffs = 0
        self.failures = 0
        self._handoff_ms = LatencyWindow()
        domain = profiler.Domain("serving")
        self._c_handoff_ms = domain.new_counter("prefill:handoff_ms")

    def on_handoff(self, ms, ok):
        with self._lock:
            if ok:
                self.handoffs += 1
            else:
                self.failures += 1
            self._handoff_ms.add(ms)
        if profiler.profiling_active():
            self._c_handoff_ms.set_value(ms)

    def snapshot(self):
        with self._lock:
            return {
                "handoffs": self.handoffs,
                "handoff_failures": self.failures,
                "handoff_ms": self._handoff_ms.percentiles(),
            }


class DisaggRouter:
    """Two-tier disaggregated serving: prefill fleet + decode fleet.

    Both tiers are full :class:`FleetRouter` instances — per-tier
    KV/queue-aware routing, breakers, drains, kills, and
    ``scaling_advice()`` all work unchanged within each tier; this class
    only adds the admission path (prefill tier) and the first-token
    handoff wiring between them.  ``serving/disagg/autoscaler.py``
    drives each tier's replica count against SLO + headroom signals.
    """

    def __init__(self, prefill_replicas=1, decode_replicas=1,
                 replica_factory=None, failover_budget=2,
                 breaker_threshold=3, breaker_backoff_ms=50.0):
        kw = dict(replica_factory=replica_factory,
                  failover_budget=failover_budget,
                  breaker_threshold=breaker_threshold,
                  breaker_backoff_ms=breaker_backoff_ms)
        self.prefill = FleetRouter(replicas=prefill_replicas, **kw)
        self.decode = FleetRouter(replicas=decode_replicas, **kw)
        self.stats_sink = DisaggStats()

    # -- model lifecycle --------------------------------------------------
    def load(self, name, prefill_factory, decode_factory,
             prefill_replicas=1, decode_replicas=1, tp=None):
        """Load one model onto both tiers.  ``prefill_factory`` must
        build engines with ``prefill_only=True`` (enforced — a full
        engine on the prefill tier would decode there and never hand
        off); ``decode_factory`` builds the engines that own streams to
        completion.  The decode tier loads FIRST so the earliest prefill
        completion already has a warm home."""
        def _wrap(n):
            eng = prefill_factory(n)
            if not getattr(eng, "prefill_only", False):
                eng.stop()
                raise MXNetError(
                    "prefill tier engine for %r must be built with "
                    "prefill_only=True" % (name,))
            eng.set_handoff(
                lambda stream, snap, _n=n: self._on_first_token(
                    _n, stream, snap))
            return eng

        self.decode.load_decode(name, decode_factory,
                                replicas=decode_replicas, tp=tp)
        try:
            self.prefill.load_decode(name, _wrap,
                                     replicas=prefill_replicas, tp=tp)
        except Exception:
            self.decode.unload_decode(name)
            raise

    def unload(self, name):
        self.prefill.unload_decode(name)
        self.decode.unload_decode(name)

    # -- admission --------------------------------------------------------
    def submit_stream(self, name, prompt, **kwargs):
        """Admit one stream at the prefill tier.  All QoS (tenant
        weights/budgets), shedding, and conservation accounting live on
        the prefill router — it holds the stream's single terminal hook,
        so ``self.prefill.decode_stats`` is the end-to-end ledger."""
        return self.prefill.submit_stream(name, prompt, **kwargs)

    def set_tenant(self, name, weight=1.0, token_budget=None):
        self.prefill.set_tenant(name, weight=weight,
                                token_budget=token_budget)

    def tenant_snapshot(self):
        return self.prefill.tenant_snapshot()

    # -- the handoff ------------------------------------------------------
    def _on_first_token(self, name, stream, snap):
        """The prefill engines' handoff sink: detach the stream from its
        prefill pin, then land it on the decode tier.  Runs on a prefill
        worker thread; returns truthy iff the stream found a decode home
        (the engine counts ``handed_off`` on truth, terminates
        UNAVAILABLE otherwise)."""
        t0 = time.monotonic()
        self.prefill.mark_departed(stream)
        try:
            ok = bool(self.decode.adopt_stream(name, stream, snap))
        except MXNetError:
            # decode tier lost the model (unload/stop race): the engine
            # terminates the stream UNAVAILABLE, prefix intact
            ok = False
        self.stats_sink.on_handoff((time.monotonic() - t0) * 1e3, ok)
        return ok

    # -- observability ----------------------------------------------------
    def stats(self):
        return {
            "prefill": self.prefill.stats(),
            "decode": self.decode.stats(),
            "disagg": self.stats_sink.snapshot(),
        }

    def scaling_advice(self):
        """Per-tier advice: each tier's own ``FleetRouter`` advice (with
        its per-engine-name breakdown) under its tier key — prefill
        reasons and decode reasons never blur, and each carries its own
        device footprint."""
        return {
            "prefill": self.prefill.scaling_advice(),
            "decode": self.decode.scaling_advice(),
        }

    def health(self, name=None):
        return {
            "prefill": self.prefill.health(name),
            "decode": self.decode.health(name),
        }

    def wait_converged(self, timeout_s=10.0):
        deadline = time.monotonic() + timeout_s
        self.prefill.wait_converged(
            timeout_s=max(0.0, deadline - time.monotonic()))
        self.decode.wait_converged(
            timeout_s=max(0.0, deadline - time.monotonic()))

    # -- lifecycle --------------------------------------------------------
    def stop(self):
        """Stop the prefill tier first — no new handoffs can originate —
        then the decode tier (in-flight adopted streams terminate
        UNAVAILABLE through each engine's drain, settling on the prefill
        router's ledger before it is read)."""
        self.prefill.stop()
        self.decode.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
