"""Disaggregated prefill/decode serving (docs/SERVING.md).

``DisaggRouter`` runs a prefill-only tier and a decode tier as two
independent ``FleetRouter`` fleets, handing each stream off at its
first token (K/V pages + sampler state + fencing token); ``Autoscaler``
drives each tier's replica count against p99 TTFT/TPOT SLOs and
KV/queue headroom.  ``serving/traffic.py`` generates the open-loop
load these are measured under (``tools/serve_bench.py --profile
disagg``)."""
from .autoscaler import Autoscaler, TierPolicy
from .router import DisaggRouter, DisaggStats

__all__ = ["Autoscaler", "TierPolicy", "DisaggRouter", "DisaggStats"]
