"""Per-model health + circuit breaking: the self-healing serving path.

A model whose backend starts failing must degrade to *fast, retryable*
rejections instead of queue-then-throw on every request (docs/ROBUSTNESS.md
has the full state machine).  Classic three-state breaker:

``closed``  — traffic flows; consecutive execute failures are counted.
``open``    — after ``failure_threshold`` consecutive failures: admission
              rejects instantly with the retryable ``UNAVAILABLE`` status
              (no queueing, no batcher wakeup, no XLA call) until the
              backoff expires.  Backoff doubles on every re-open, capped.
``half_open`` — backoff expired: exactly one in-flight *probe* batch is
              admitted.  Success closes the breaker (and resets the
              backoff); failure re-opens it with the doubled backoff.  A
              probe that never reports (e.g. timed out in queue) releases
              its slot after ``probe_timeout_s`` so recovery cannot wedge.

Health is derived, not stored: ``closed`` with a clean streak is HEALTHY,
``closed`` mid-streak or ``half_open`` is DEGRADED, ``open`` is UNAVAILABLE.
The breaker records outcomes per *batch execution* (the unit that actually
fails), and every transition is counted for ``ModelServer.stats()`` and the
profiler Domain counters in stats.py.
"""
from __future__ import annotations

import threading
import time

__all__ = ["HEALTHY", "DEGRADED", "UNAVAILABLE_HEALTH", "CircuitBreaker",
           "ADMIT", "PROBE", "REJECT", "HEALTH_RANK", "worst_health"]

# health states (UNAVAILABLE the request *status* lives in server.py;
# UNAVAILABLE_HEALTH is the same word as a *health* level)
HEALTHY = "HEALTHY"
DEGRADED = "DEGRADED"
UNAVAILABLE_HEALTH = "UNAVAILABLE"

# severity order for aggregating health across replicas/engines
HEALTH_RANK = {HEALTHY: 0, DEGRADED: 1, UNAVAILABLE_HEALTH: 2}


def worst_health(levels):
    """The most severe level in ``levels`` (HEALTHY when empty)."""
    worst = HEALTHY
    for level in levels:
        if HEALTH_RANK.get(level, 2) > HEALTH_RANK[worst]:
            worst = level
    return worst

# admit() decisions
ADMIT = "admit"
PROBE = "probe"
REJECT = "reject"

_CLOSED, _OPEN, _HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """K-consecutive-failure breaker with half-open probing and capped
    exponential backoff.  Thread-safe; every field is guarded by ``_lock``
    (admission runs on client threads, outcomes on the batcher worker)."""

    def __init__(self, failure_threshold=5, backoff_s=0.05, max_backoff_s=2.0,
                 probe_timeout_s=None, clock=time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self._lock = threading.Lock()
        self._clock = clock
        self._threshold = int(failure_threshold)
        self._base_backoff = float(backoff_s)
        self._max_backoff = float(max_backoff_s)
        self._probe_timeout = (float(probe_timeout_s)
                               if probe_timeout_s is not None
                               else max(4 * self._base_backoff, 1.0))
        self._state = _CLOSED
        self._consecutive = 0
        self._backoff = self._base_backoff
        self._open_until = 0.0
        self._probe_expire = None   # monotonic deadline while a probe runs
        self._opens = 0             # lifetime open transitions
        self._rejections = 0        # fast-rejected admissions

    # -- admission (client threads) -------------------------------------
    def admit(self):
        """ADMIT (closed), PROBE (half-open slot granted), or REJECT."""
        with self._lock:
            if self._state == _CLOSED:
                return ADMIT
            now = self._clock()
            if self._state == _OPEN and now >= self._open_until:
                self._state = _HALF_OPEN
                self._probe_expire = None
            if self._state == _HALF_OPEN and (
                    self._probe_expire is None or now >= self._probe_expire):
                # grant the (single) probe slot; auto-expire so a probe
                # lost to a queue timeout cannot wedge recovery forever
                self._probe_expire = now + self._probe_timeout
                return PROBE
            self._rejections += 1
            return REJECT

    def release_probe(self):
        """Return an unused probe slot (the probe request never reached
        execution — invalid input, shed, shutdown).  Without this, a
        stream of non-executing requests could hold the slot for
        ``probe_timeout_s`` at a time and starve recovery."""
        with self._lock:
            if self._state == _HALF_OPEN:
                self._probe_expire = None

    # -- outcomes (batcher worker) --------------------------------------
    def on_success(self):
        with self._lock:
            self._consecutive = 0
            self._probe_expire = None
            if self._state != _CLOSED:
                self._state = _CLOSED
                self._backoff = self._base_backoff

    def on_failure(self):
        """One failed batch execution; returns True if this opened it."""
        with self._lock:
            self._consecutive += 1
            now = self._clock()
            if self._state == _HALF_OPEN:
                # failed probe: re-open with doubled (capped) backoff
                self._state = _OPEN
                self._opens += 1
                self._backoff = min(self._backoff * 2, self._max_backoff)
                self._open_until = now + self._backoff
                self._probe_expire = None
                return True
            if self._state == _CLOSED and \
                    self._consecutive >= self._threshold:
                self._state = _OPEN
                self._opens += 1
                self._open_until = now + self._backoff
                return True
            return False

    def reset(self):
        """Forget all failure history: closed, clean streak, base backoff.

        Used when the object the breaker guards is *replaced* rather than
        recovered — e.g. a rolling weight swap retires the copy whose
        failures were counted (serving/deploy.py) — so stale history from
        the old copy neither rejects traffic to the new one nor masks its
        fresh failures.  Lifetime ``opens``/``rejections`` counters are
        kept (they describe the slot, not the copy)."""
        with self._lock:
            self._state = _CLOSED
            self._consecutive = 0
            self._backoff = self._base_backoff
            self._open_until = 0.0
            self._probe_expire = None

    # -- observability ---------------------------------------------------
    def state(self):
        with self._lock:
            return self._state

    def health(self):
        """Derived health level (see module docstring)."""
        with self._lock:
            if self._state == _OPEN:
                return UNAVAILABLE_HEALTH
            if self._state == _HALF_OPEN or self._consecutive > 0:
                return DEGRADED
            return HEALTHY

    def snapshot(self):
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "failure_threshold": self._threshold,
                "backoff_s": self._backoff,
                "opens": self._opens,
                "rejections": self._rejections,
            }
