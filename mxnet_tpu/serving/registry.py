"""Multi-model registry: loadable, warmable, inference-mode CachedOps.

A ``ServableModel`` wraps one model — a hybridizable Gluon block or an
exported symbol+params pair re-imported as a SymbolBlock — as an
inference-mode :class:`~mxnet_tpu.cached_op.CachedOp` plus its bucket menu
(admissible input shapes x batch ladder).  ``warmup()`` dispatches a zeros
batch for every (shape, rung) pair at load time so XLA compiles the entire
menu before traffic arrives; after that, ``CachedOp.cache_stats()`` must show
zero new misses in steady state (the acceptance gate tests/test_serving.py
asserts).

The registry itself is a flat name -> ServableModel map guarded by one lock;
models load/unload independently and hold no shared mutable state.
"""
from __future__ import annotations

import threading

import numpy as np

from .. import autograd
from .. import faults
from .. import util
from ..base import MXNetError
from .buckets import BucketLadder, normalize_shape_variants, shape_key
from .health import CircuitBreaker
from .stats import ModelStats

__all__ = ["ServableModel", "ModelRegistry"]

# retry envelope around one batch execution: transient backend faults are
# absorbed here (docs/ROBUSTNESS.md policy table); anything that outlasts
# the budget surfaces to the batcher as the batch failure it is
_EXEC_ATTEMPTS = 3
_EXEC_BACKOFF_S = 0.002


class ServableModel:
    """One loaded model: CachedOp + bucket menu + per-model stats +
    circuit breaker (health.py)."""

    def __init__(self, name, block, input_shapes, dtype="float32",
                 max_batch=8, batch_ladder=None, flags=None,
                 breaker_threshold=5, breaker_backoff_ms=50.0,
                 breaker_max_backoff_ms=2000.0, generation=None):
        self.name = name
        self.block = block
        # weight generation tag (serving/deploy.py): which checkpoint epoch
        # this copy's params came from; None = untagged standalone use
        self.generation = generation
        self.ladder = (batch_ladder if isinstance(batch_ladder, BucketLadder)
                       else BucketLadder(max_batch, batch_ladder))
        self.variants = normalize_shape_variants(input_shapes)
        n_inputs = len(self.variants[0])
        if any(len(v) != n_inputs for v in self.variants):
            raise ValueError("all shape variants must have the same number "
                             "of inputs")
        self.n_inputs = n_inputs
        if isinstance(dtype, (list, tuple)):
            if len(dtype) != n_inputs:
                raise ValueError("need one dtype per input")
            self.dtypes = [np.dtype(d) for d in dtype]
        else:
            self.dtypes = [np.dtype(dtype)] * n_inputs
        self._ensure_initialized(block)
        # own CachedOp instance (never perturbs the block's hybridize cache),
        # built by the one shared construction point in gluon.block
        from ..gluon.block import build_cached_op
        self._cop, params = build_cached_op(block, flags)
        self._params = {n: p.data() for n, p in params.items()}
        self.stats = ModelStats(name)
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_threshold,
            backoff_s=breaker_backoff_ms / 1e3,
            max_backoff_s=breaker_max_backoff_ms / 1e3)
        self._execute_retry = util.retry(
            attempts=_EXEC_ATTEMPTS, backoff=_EXEC_BACKOFF_S,
            on_retry=lambda exc, i: self.stats.on_retry())(self._execute_once)
        self.warmup_report = None
        # every admissible (per-request shapes, dtypes) coalescing key
        self.allowed_keys = frozenset(
            tuple((shape, str(dt)) for shape, dt in zip(v, self.dtypes))
            for v in self.variants)

    def _ensure_initialized(self, block):
        """Finish deferred parameter init with a zeros probe if needed."""
        try:
            for p in block.collect_params().values():
                p.data()
            return
        except Exception:
            pass
        from .. import ndarray as nd
        probe = [nd.zeros((1,) + v, dtype=str(dt))
                 for v, dt in zip(self.variants[0], self.dtypes)]
        with autograd.pause():
            block(*probe)

    # ------------------------------------------------------------------
    def execute(self, batch_arrays):
        """Run one padded batch (numpy, batch-major) -> list of numpy
        outputs, still batch-major.  Inference mode regardless of the
        caller thread's autograd state.

        The XLA call sits behind the ``serving.predict`` fault point and a
        transient-retry envelope (docs/ROBUSTNESS.md): a flaky backend
        costs latency, not a failed batch.  Failures that outlast the
        budget propagate to the batcher, which fails the batch and reports
        to the circuit breaker."""
        return self._execute_retry(batch_arrays)

    def _execute_once(self, batch_arrays):
        from ..ndarray import NDArray
        faults.fault_point("serving.predict", model=self.name)
        inputs = [NDArray(np.ascontiguousarray(a)) for a in batch_arrays]
        with autograd.pause():
            out = self._cop(self._params, *inputs)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        return [o.asnumpy() for o in outs]  # mxflow: sync-ok(serving boundary: predict results materialize for the response)

    def warmup(self):
        """Precompile every (shape variant, ladder rung) signature.

        Returns {"signatures": n, "compiles": misses_delta, "skipped": m}
        and stores it as ``self.warmup_report``.  Load-time cost, steady-
        state zero-recompile guarantee."""
        before = self._cop.cache_stats()["misses"]
        n = 0
        for variant in self.variants:
            for rung in self.ladder:
                arrays = [np.zeros((rung,) + shape, dt)
                          for shape, dt in zip(variant, self.dtypes)]
                self.execute(arrays)
                n += 1
        after = self._cop.cache_stats()
        self.warmup_report = {
            "signatures": n,
            "compiles": after["misses"] - before,
            "cache": {"hits": after["hits"], "misses": after["misses"]},
        }
        return self.warmup_report

    def cache_stats(self):
        return self._cop.cache_stats()

    def admissible(self, arrays):
        return shape_key(arrays) in self.allowed_keys


class ModelRegistry:
    """Thread-safe name -> ServableModel map."""

    def __init__(self):
        self._lock = threading.Lock()
        self._models = {}

    def add(self, model):
        with self._lock:
            if model.name in self._models:
                raise MXNetError("model %r is already loaded" % model.name)
            self._models[model.name] = model

    def remove(self, name):
        with self._lock:
            try:
                return self._models.pop(name)
            except KeyError:
                raise MXNetError("no model %r; loaded: %s"
                                 % (name, sorted(self._models) or "none"))

    def get(self, name):
        with self._lock:
            try:
                return self._models[name]
            except KeyError:
                raise MXNetError("no model %r; loaded: %s"
                                 % (name, sorted(self._models) or "none"))

    def names(self):
        with self._lock:
            return sorted(self._models)
