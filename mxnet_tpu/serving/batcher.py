"""Dynamic micro-batcher: bounded admission queue + coalescing worker.

One ``MicroBatcher`` (one worker thread) per loaded model:

* **admission** — ``submit()`` either enqueues or refuses immediately when
  the bounded queue is full (load-shedding backpressure: the caller gets an
  OVERLOADED status now instead of the queue growing until the process
  OOMs).  The reference engine has the same discipline at the C++ boundary
  (bounded ThreadedEngine task queues).
* **coalescing** — the worker pops the oldest request, lingers up to
  ``linger_ms`` for companions with the SAME shape key (different shapes
  never mix: batch-dim padding is exact, feature-dim padding is not — see
  buckets.py), then executes one batch padded to the smallest ladder rung.
* **deadlines** — a request whose deadline passed while queued or lingering
  completes with TIMEOUT *without* executing; the linger window is clipped
  so a lone request dispatches a little before its deadline rather than
  expiring in the queue.

The worker holds the lock only to move requests between queue and batch;
execution (the XLA call) runs unlocked, so submitters never block on
compute.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from .buckets import shape_key

__all__ = ["Request", "MicroBatcher"]

# linger is clipped to (deadline - margin) so a near-deadline request is
# dispatched rather than expired while waiting for companions
_DEADLINE_MARGIN_S = 0.005


class Request:
    """One in-flight inference request (also the async result handle)."""

    __slots__ = ("inputs", "key", "t_enqueue", "deadline", "status",
                 "outputs", "error", "latency_ms", "stats", "_event",
                 "_done_lock")

    def __init__(self, inputs, deadline=None, stats=None):
        self.inputs = tuple(inputs)          # per-request numpy arrays
        self.key = shape_key(self.inputs)
        self.t_enqueue = time.monotonic()
        self.deadline = deadline             # monotonic seconds or None
        # the owning model's ModelStats, attached at submission so a
        # claimant can keep the terminal counters conserved even after the
        # model/server entry is torn down (result() across unload)
        self.stats = stats
        self.status = None
        self.outputs = None
        self.error = None
        self.latency_ms = None
        self._event = threading.Event()
        self._done_lock = threading.Lock()

    def expired(self, now=None):
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                >= self.deadline)

    def complete(self, status, outputs=None, error=None):
        """First completion wins (client timeout vs worker result race)."""
        with self._done_lock:
            if self.status is not None:
                return False
            self.outputs = outputs
            self.error = error
            self.latency_ms = (time.monotonic() - self.t_enqueue) * 1e3
            # status is assigned LAST: it is the done flag every racing
            # reader keys on, so a terminal status must never be visible
            # before the fields that go with it
            self.status = status
        self._event.set()
        return True

    def wait(self, timeout=None):
        return self._event.wait(timeout)

    def snapshot(self):
        """Atomic read of the terminal state.

        Readers must NOT sample ``status``/``outputs``/``latency_ms`` as
        separate unlocked reads: a deadline expiry racing a batch
        completion could interleave them and pair a TIMEOUT status with
        the other completion's outputs (the torn-read this method
        regression-tests against under tools/mxstress.py)."""
        with self._done_lock:
            return (self.status, self.outputs, self.latency_ms, self.error)


class MicroBatcher:
    def __init__(self, model, max_queue=64, linger_ms=2.0):
        self._model = model
        self._stats = model.stats
        self._max_queue = int(max_queue)
        self._linger_s = float(linger_ms) / 1e3
        self._queue = deque()
        self._cond = threading.Condition()
        self._running = True
        self._paused = False
        self._thread = threading.Thread(
            target=self._run, name="mx-serve-%s" % model.name, daemon=True)
        self._thread.start()

    # -- client side ----------------------------------------------------
    def submit(self, request):
        """Admit or refuse.  Returns True when admitted, else the refusal
        reason: ``"full"`` (a shed was counted here) or ``"stopping"``
        (lifecycle — counted by the caller as its one UNAVAILABLE, never
        double-counted with shed)."""
        with self._cond:
            if not self._running:
                return "stopping"
            if len(self._queue) >= self._max_queue:
                self._stats.on_shed()
                return "full"
            self._queue.append(request)
            self._stats.on_admitted()
            self._stats.on_queue_depth(len(self._queue))
            self._cond.notify_all()
        return True

    def pause(self):
        """Stop dispatching (drain/maintenance); queue keeps admitting."""
        with self._cond:
            self._paused = True

    def resume(self):
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    @property
    def running(self):
        with self._cond:
            return self._running

    def stop(self):
        """Tear down; every queued request terminates with the retryable
        UNAVAILABLE status (shutdown is a lifecycle event, not a model
        error) — no waiter is ever left hanging on a dead queue."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
        self._thread.join(timeout=5)
        from .server import UNAVAILABLE
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
        for r in leftovers:
            if r.complete(UNAVAILABLE, error="server shutting down"):
                self._stats.on_result(UNAVAILABLE, r.latency_ms)

    # -- worker side ----------------------------------------------------
    def _run(self):
        from .server import TIMEOUT
        while True:
            with self._cond:
                while self._running and (self._paused or not self._queue):
                    self._cond.wait(0.05)
                if not self._running:
                    return
                first = self._queue.popleft()
                self._stats.on_queue_depth(len(self._queue))
            if first.expired():
                if first.complete(TIMEOUT):
                    self._stats.on_result(TIMEOUT, first.latency_ms)
                continue

            self._linger(first)
            batch = self._gather(first)
            now = time.monotonic()
            live = []
            for r in batch:
                if r.expired(now):
                    if r.complete(TIMEOUT):
                        self._stats.on_result(TIMEOUT, r.latency_ms)
                else:
                    live.append(r)
            if live:
                self._execute(live)

    def _linger(self, first):
        """Wait for same-shape companions, bounded by linger window and
        the first request's deadline margin."""
        until = first.t_enqueue + self._linger_s
        if first.deadline is not None:
            until = min(until, first.deadline - _DEADLINE_MARGIN_S)
        max_more = self._model.ladder.max_batch - 1
        with self._cond:
            while self._running:
                now = time.monotonic()
                same = sum(1 for r in self._queue if r.key == first.key)
                if same >= max_more or now >= until:
                    return
                self._cond.wait(until - now)

    def _gather(self, first):
        """Pop up to max_batch same-shape requests; others keep their
        queue order for the next iteration."""
        batch = [first]
        with self._cond:
            skipped = []
            while self._queue and len(batch) < self._model.ladder.max_batch:
                r = self._queue.popleft()
                if r.key == first.key:
                    batch.append(r)
                else:
                    skipped.append(r)
            for r in reversed(skipped):
                self._queue.appendleft(r)
            self._stats.on_queue_depth(len(self._queue))
        return batch

    def _execute(self, batch):
        from .server import OK, ERROR
        import numpy as np
        n = len(batch)
        bucket = self._model.ladder.bucket(n)
        arrays = []
        for i in range(self._model.n_inputs):
            stacked = np.stack([r.inputs[i] for r in batch])
            if bucket > n:
                pad = np.zeros((bucket - n,) + stacked.shape[1:],
                               stacked.dtype)
                stacked = np.concatenate([stacked, pad])
            arrays.append(stacked)
        t0 = time.monotonic()
        breaker = getattr(self._model, "breaker", None)
        try:
            outs = self._model.execute(arrays)
        except Exception as exc:  # model bug: fail the batch, keep serving
            if breaker is not None:
                breaker.on_failure()
                self._stats.on_breaker_state(breaker.state())
            for r in batch:
                if r.complete(ERROR, error=repr(exc)):
                    self._stats.on_result(ERROR, r.latency_ms)
            return
        if breaker is not None:
            # success closes a half-open breaker (the probe path) and
            # resets the failure streak
            was_closed = breaker.state() == "closed"
            breaker.on_success()
            if not was_closed:
                self._stats.on_breaker_state(breaker.state())
        batch_ms = (time.monotonic() - t0) * 1e3
        self._stats.on_batch(n, bucket, batch_ms)
        for i, r in enumerate(batch):
            # first-completion-wins: a client that already timed out locally
            # keeps its TIMEOUT status and must not be double-counted
            if r.complete(OK, [o[i] for o in outs]):
                self._stats.on_result(OK, r.latency_ms)
