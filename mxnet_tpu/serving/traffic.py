"""Open-loop traffic generation for serving benchmarks.

Every decode bench before this module was CLOSED-loop: N client threads
each submit, wait, submit again — so the arrival rate adapts to the
system under test, and a slow server conveniently slows its own load
down.  Production traffic does not wait: users arrive when they arrive.
Goodput-under-SLO (the number serving is actually measured by) only
means something under an **open loop**, where arrivals are a fixed
seeded schedule and a struggling system visibly blows its tail latency
instead of quietly throttling the benchmark.

Three arrival processes, all driven by one ``random.Random(seed)`` (same
seed => bit-identical trace, the reproducibility contract every bench
artifact and test leans on):

* :func:`poisson_trace` — homogeneous Poisson arrivals at ``rate_hz``
  (exponential inter-arrival gaps), the memoryless baseline.
* :func:`bursty_trace` — a square-wave modulated Poisson process:
  periodic burst windows run at ``burst_factor`` times the base rate
  (flash crowds, retry storms).
* :func:`diurnal_trace` — a sinusoidally modulated Poisson process
  (the day/night cycle compressed into ``period_s``), via Lewis-Shedler
  thinning against the peak rate.

:func:`tenant_mix` assigns each arrival a tenant by seeded weighted
draw, and :func:`replay` fires a trace against a submit callable in
real (or scaled) time WITHOUT waiting on completions — the open loop
itself.  ``tools/serve_bench.py --profile disagg`` is the standing
consumer; tests/test_disagg.py gates reproducibility and
arrival-count conservation.
"""
from __future__ import annotations

import math
import random
import time

__all__ = ["poisson_trace", "bursty_trace", "diurnal_trace", "tenant_mix",
           "replay"]


def _thinned(rate_fn, max_rate, duration_s, rng):
    """Lewis-Shedler thinning: draw candidate arrivals from a Poisson
    process at ``max_rate`` and keep each with probability
    ``rate_fn(t) / max_rate`` — an exact sampler for any intensity
    bounded by ``max_rate``, consuming the RNG in arrival order so the
    trace is a pure function of (intensity, seed)."""
    out = []
    t = 0.0
    while True:
        t += rng.expovariate(max_rate)
        if t >= duration_s:
            return out
        if rng.random() < rate_fn(t) / max_rate:
            out.append(t)


def poisson_trace(rate_hz, duration_s, seed=0):
    """Sorted arrival offsets (seconds in ``[0, duration_s)``) of a
    homogeneous Poisson process at ``rate_hz``."""
    if rate_hz <= 0:
        raise ValueError("rate_hz must be > 0, got %r" % (rate_hz,))
    if duration_s <= 0:
        raise ValueError("duration_s must be > 0, got %r" % (duration_s,))
    rng = random.Random(seed)
    out = []
    t = rng.expovariate(rate_hz)
    while t < duration_s:
        out.append(t)
        t += rng.expovariate(rate_hz)
    return out


def bursty_trace(rate_hz, duration_s, seed=0, burst_factor=4.0,
                 burst_fraction=0.25, n_bursts=4):
    """Square-wave bursty arrivals: ``n_bursts`` evenly spaced windows,
    each covering the first ``burst_fraction`` of its period, run at
    ``burst_factor * rate_hz``; the rest of the time runs at the base
    rate.  Models flash crowds / synchronized retry storms."""
    if rate_hz <= 0:
        raise ValueError("rate_hz must be > 0, got %r" % (rate_hz,))
    if duration_s <= 0:
        raise ValueError("duration_s must be > 0, got %r" % (duration_s,))
    if burst_factor < 1.0:
        raise ValueError("burst_factor must be >= 1, got %r"
                         % (burst_factor,))
    if not 0.0 < burst_fraction < 1.0:
        raise ValueError("burst_fraction must be in (0, 1), got %r"
                         % (burst_fraction,))
    if n_bursts < 1:
        raise ValueError("n_bursts must be >= 1, got %r" % (n_bursts,))
    period = duration_s / float(n_bursts)

    def rate(t):
        in_burst = (t % period) < burst_fraction * period
        return rate_hz * (burst_factor if in_burst else 1.0)

    return _thinned(rate, rate_hz * burst_factor, duration_s,
                    random.Random(seed))


def diurnal_trace(rate_hz, duration_s, seed=0, period_s=None, depth=0.8):
    """Sinusoidally modulated arrivals: intensity
    ``rate_hz * (1 + depth * sin(2*pi*t / period_s))`` — the day/night
    cycle compressed into ``period_s`` (default: the whole duration is
    one cycle).  ``depth`` in [0, 1) sets how deep the trough goes."""
    if rate_hz <= 0:
        raise ValueError("rate_hz must be > 0, got %r" % (rate_hz,))
    if duration_s <= 0:
        raise ValueError("duration_s must be > 0, got %r" % (duration_s,))
    if not 0.0 <= depth < 1.0:
        raise ValueError("depth must be in [0, 1), got %r" % (depth,))
    period = float(period_s) if period_s is not None else float(duration_s)
    if period <= 0:
        raise ValueError("period_s must be > 0, got %r" % (period_s,))

    def rate(t):
        return rate_hz * (1.0 + depth * math.sin(2.0 * math.pi * t / period))

    return _thinned(rate, rate_hz * (1.0 + depth), duration_s,
                    random.Random(seed))


def tenant_mix(arrivals, weights, seed=0):
    """Assign each arrival a tenant by seeded weighted draw; returns a
    list of tenant names aligned with ``arrivals``.  ``weights`` maps
    tenant name -> positive weight; the draw order consumes one uniform
    per arrival, so the assignment is a pure function of
    (len(arrivals), weights, seed)."""
    if not weights:
        raise ValueError("weights must name at least one tenant")
    names = sorted(weights)
    cum = []
    total = 0.0
    for name in names:
        w = float(weights[name])
        if w <= 0:
            raise ValueError("tenant %r weight must be > 0, got %r"
                             % (name, weights[name]))
        total += w
        cum.append(total)
    rng = random.Random(seed)
    out = []
    for _ in arrivals:
        u = rng.random() * total
        for name, edge in zip(names, cum):
            if u < edge:
                out.append(name)
                break
        else:
            out.append(names[-1])
    return out


def replay(arrivals, submit, time_scale=1.0, now=None, sleep=None):
    """Fire ``submit(i, t)`` at each scheduled offset — the open loop.

    Arrivals are honored on the wall clock (scaled by ``time_scale``;
    0.5 replays twice as fast) REGARDLESS of what earlier submissions
    are doing: nothing here waits on a stream, so a backed-up system
    keeps receiving load exactly like production.  When the clock has
    already passed an arrival's offset (the submit path itself was
    slow), the submission fires immediately — arrivals are never
    dropped.  Returns the number of submissions fired, which tests
    hold equal to ``len(arrivals)`` (arrival-count conservation).

    ``now``/``sleep`` inject clocks for tests; defaults are
    ``time.monotonic`` / ``time.sleep``."""
    if time_scale <= 0:
        raise ValueError("time_scale must be > 0, got %r" % (time_scale,))
    now = now if now is not None else time.monotonic
    sleep = sleep if sleep is not None else time.sleep
    t0 = now()
    fired = 0
    for i, t in enumerate(arrivals):
        due = t0 + float(t) * time_scale
        while True:
            delta = due - now()
            if delta <= 0:
                break
            sleep(min(delta, 0.05))
        submit(i, float(t))
        fired += 1
    return fired
