"""Elastic serving fleet: health-routed predicts across N server replicas.

One :class:`~mxnet_tpu.serving.server.ModelServer` is one *replica*; this
router is the tier above it — the dynamic-membership story of the TensorFlow
paper (replicas come and go; the system reroutes, drains, and resumes) made
concrete for the serving path:

* **Placement** — ``load_model(name, ..., replicas=k)`` spreads the model
  over the k least-loaded live replicas; every copy is warmed (the full
  bucket-menu precompile) before it takes traffic.
* **Health-routed selection** — one ``serving/health.py`` CircuitBreaker per
  (model, replica) pair, fed by what the *router* observes: an UNAVAILABLE
  result or an unreachable/dead replica is a failure, any answered request
  is a success.  Selection rotates round-robin over the model's placement,
  skipping DRAINING/DEAD replicas and open breakers.
* **Bounded failover** — a predict that lands on a dead or UNAVAILABLE
  replica is retried on the next routable one, at most ``failover_budget``
  times; the request reaches exactly one terminal status either way, so
  fleet conservation (``requests == ok + timeouts + errors + unavailable``)
  holds across failovers.
* **Drain** — ``drain(rid)`` stops admission to a replica while its
  in-flight requests finish (the replica's server keeps running); new
  submissions that have nowhere else to go get UNAVAILABLE with a
  ``draining`` reason.  ``enable(rid)`` restores routing.
* **Rebalance** — when a replica joins (``add_replica``) or dies, every
  under-replicated model is re-loaded — *and re-warmed* — on a new replica
  BEFORE the placement cutover, so failover never recompiles in the hot
  path.  Death-triggered rebalancing runs on a background thread; the dying
  request has already failed over to an existing warm copy.

Replica death is observed, not announced: a ``faults.SimulatedCrash``
injected at the ``fleet.replica`` site (or an explicit ``kill_replica``)
models the replica process dying mid-request.  This is the one site where
production code catches SimulatedCrash — the router IS the surviving
process (see faults.py).

**Stateful decode tier.**  ``predict()`` traffic is stateless — any warm
replica can serve any request — but decode streams are not: a stream's KV
pages live on exactly one replica.  ``load_decode()`` places DecodeEngines
the way ``load_model`` places models, and ``submit_stream()`` routes each
NEW stream onto the replica with the most free KV blocks and the
shallowest queue (weighted score over the engine's live
``routing_signals()``), after which **session affinity** pins every token
of that stream to its placement.  The lifecycle verbs then honor the
state:

* ``drain(rid)`` performs a **fenced KV handoff**: each engine on the
  replica quiesces at a step boundary, every live stream's token prefix +
  K/V pages are exported, the replica's lease generation bumps (the
  fencing token — a zombie presenting the old generation can neither emit
  nor import), and the router resumes each stream on a survivor via
  ``import_stream`` — the merged stream is bitwise-equal to an
  uninterrupted one.
* ``kill_replica(rid)``/crash (no snapshot exists) terminates the
  replica's streams UNAVAILABLE with their valid prefix within a bounded
  deadline — never a hang — and the client re-admits with
  ``prompt + prefix`` as the new prompt.
* **Multi-tenant QoS**: ``set_tenant(name, weight, token_budget)`` gives
  every tenant a weighted-fair share of the fleet's KV token capacity; an
  over-budget tenant sheds OVERLOADED while the rest keep flowing.
  ``scaling_advice()``/``poll_scaling()`` turn breaker + KV-utilization
  signals into scale-out/scale-in policy hooks, with a per-engine-name
  breakdown; ``scale_decode()`` closes the loop into an actual replica
  retarget (serving/disagg/autoscaler.py is the standing driver).
* **Cross-tier handoff**: ``adopt_stream()`` lands a snapshot exported
  by ANOTHER router's tier on this fleet's best replica, and
  ``mark_departed()`` detaches a handed-off stream from its local
  replica pin without dropping its accounting rec — together they are
  the primitive pair the disaggregated prefill/decode topology
  (serving/disagg/) is built from.

The ``fleet`` and ``decode_fleet`` mxstress scenarios
(analysis/schedule.py) are the standing chaos consumers: replicas are
killed and drained under (multi-tenant) storm load and zero requests or
streams may drop, prefixes stay whole, KV pools stay leak-free, and the
router must re-converge HEALTHY.  See docs/ROBUSTNESS.md ("Fleet
membership", "Stream handoff") and docs/SERVING.md (topology).
"""
from __future__ import annotations

import threading
import time

from .. import faults
from ..base import MXNetError
from ..kvstore_server import MembershipTable
from .health import (CircuitBreaker, HEALTHY, DEGRADED, UNAVAILABLE_HEALTH,
                     REJECT, worst_health)
from .server import (ModelServer, InferenceResult,
                     OK, TIMEOUT, ERROR, UNAVAILABLE, OVERLOADED,
                     INVALID_INPUT)
from .stats import LatencyWindow

__all__ = ["FleetRouter", "FleetStats", "DecodeFleetStats",
           "LIVE", "DRAINING", "DEAD"]

# replica lifecycle states
LIVE = "LIVE"          # routable
DRAINING = "DRAINING"  # no new admissions; in-flight requests finish
DEAD = "DEAD"          # crashed or removed; never routable again


class FleetStats:
    """Fleet-level counters.  Thread-safe; same two-tier split as
    ModelStats: ``requests`` counts routed client calls that reached a
    terminal OK/TIMEOUT/ERROR/UNAVAILABLE status (the conservation set);
    ``shed``/``invalid`` count pass-through fast rejections outside it."""

    def __init__(self):
        self._lock = threading.Lock()
        self.requests = 0
        self.ok = 0
        self.timeouts = 0
        self.errors = 0
        self.unavailable = 0
        self.shed = 0            # OVERLOADED passed through from a replica
        self.invalid = 0         # INVALID_INPUT passed through
        self.failovers = 0       # attempts re-routed to another replica
        self.replica_deaths = 0
        self.rebalances = 0      # placement commits after a re-warm
        self._lat = LatencyWindow()

    def on_result(self, status, latency_ms=None):
        with self._lock:
            if status == OK:
                self.requests += 1
                self.ok += 1
            elif status == TIMEOUT:
                self.requests += 1
                self.timeouts += 1
            elif status == ERROR:
                self.requests += 1
                self.errors += 1
            elif status == UNAVAILABLE:
                self.requests += 1
                self.unavailable += 1
            elif status == OVERLOADED:
                self.shed += 1
            elif status == INVALID_INPUT:
                self.invalid += 1
            if latency_ms is not None:
                self._lat.add(latency_ms)

    def on_failover(self):
        with self._lock:
            self.failovers += 1

    def on_replica_death(self):
        with self._lock:
            self.replica_deaths += 1

    def on_rebalance(self):
        with self._lock:
            self.rebalances += 1

    def snapshot(self):
        with self._lock:
            return {
                "requests": self.requests,
                "ok": self.ok,
                "timeouts": self.timeouts,
                "errors": self.errors,
                "unavailable": self.unavailable,
                "shed": self.shed,
                "invalid": self.invalid,
                "failovers": self.failovers,
                "replica_deaths": self.replica_deaths,
                "rebalances": self.rebalances,
                "latency_ms": self._lat.percentiles(),
            }


class DecodeFleetStats:
    """Router-level counters for the stateful decode tier.  Thread-safe;
    same two-tier split as FleetStats: ``requests`` counts streams the
    router ADMITTED and every one of them reaches exactly one terminal
    OK/TIMEOUT/ERROR/UNAVAILABLE count — across handoffs — so
    ``requests == ok + timeouts + errors + unavailable`` is the chaos
    gate's conservation invariant; ``shed`` (QoS/engine OVERLOADED),
    ``invalid`` and ``unavailable_rejected`` count fast rejections that
    never enter it."""

    def __init__(self):
        self._lock = threading.Lock()
        self.requests = 0
        self.ok = 0
        self.timeouts = 0
        self.errors = 0
        self.unavailable = 0
        self.shed = 0
        self.invalid = 0
        self.unavailable_rejected = 0
        self.handoffs = 0        # streams resumed on a survivor
        self.failovers = 0       # placement attempts re-routed
        self.fenced = 0          # streams terminated by a fence token
        self.tokens_out = 0      # tokens delivered across terminal streams
        self._lat = LatencyWindow()
        self._ttft = LatencyWindow()
        self._tpot = LatencyWindow()   # per-token decode latency (ms)

    def on_admitted(self):
        with self._lock:
            self.requests += 1

    def on_shed(self):
        with self._lock:
            self.shed += 1

    def on_invalid(self):
        with self._lock:
            self.invalid += 1

    def on_unavailable_rejected(self):
        with self._lock:
            self.unavailable_rejected += 1

    def on_handoff(self):
        with self._lock:
            self.handoffs += 1

    def on_failover(self):
        with self._lock:
            self.failovers += 1

    def on_fenced(self):
        with self._lock:
            self.fenced += 1

    def on_result(self, status, latency_ms=None, ttft_ms=None, tokens=0):
        with self._lock:
            if status == OK:
                self.ok += 1
            elif status == TIMEOUT:
                self.timeouts += 1
            elif status == ERROR:
                self.errors += 1
            elif status == UNAVAILABLE:
                self.unavailable += 1
            else:
                return   # OVERLOADED/INVALID never register a stream rec
            self.tokens_out += int(tokens)
            if latency_ms is not None:
                self._lat.add(latency_ms)
            if ttft_ms is not None:
                self._ttft.add(ttft_ms)
            if int(tokens) > 1 and latency_ms is not None \
                    and ttft_ms is not None:
                # time-per-output-token: decode-phase latency spread over
                # the tokens after the first (the TPOT SLO's sample)
                self._tpot.add(max(0.0, latency_ms - ttft_ms)
                               / (int(tokens) - 1))

    def snapshot(self):
        with self._lock:
            return {
                "requests": self.requests,
                "ok": self.ok,
                "timeouts": self.timeouts,
                "errors": self.errors,
                "unavailable": self.unavailable,
                "shed": self.shed,
                "invalid": self.invalid,
                "unavailable_rejected": self.unavailable_rejected,
                "handoffs": self.handoffs,
                "failovers": self.failovers,
                "fenced": self.fenced,
                "tokens_out": self.tokens_out,
                "latency_ms": self._lat.percentiles(),
                "ttft_ms": self._ttft.percentiles(),
                "tpot_ms": self._tpot.percentiles(),
            }


class _Replica:
    """One replica row; every field except ``server`` is guarded by the
    router's ``_lock`` (``server`` is assigned once and never rebound)."""

    __slots__ = ("rid", "server", "state", "inflight", "gen")

    def __init__(self, rid, server):
        self.rid = rid
        self.server = server
        self.state = LIVE
        self.inflight = 0
        self.gen = 0             # current lease generation (fencing token)


class _ModelSpec:
    """Everything needed to re-load a model on a joining replica.
    ``wgen`` is the weight generation the spec currently serves (None
    until a deployment commits one); rebalance passes compare it at
    commit time so a copy warmed from a superseded generation is rolled
    back instead of routed."""

    __slots__ = ("name", "block", "input_shapes", "replicas", "kwargs",
                 "wgen")

    def __init__(self, name, block, input_shapes, replicas, kwargs):
        self.name = name
        self.block = block
        self.input_shapes = input_shapes
        self.replicas = replicas
        self.kwargs = kwargs
        self.wgen = None


class _EngineSpec:
    """Everything needed to re-build a decode engine on a joining replica.
    ``factory(name)`` must return a warmed DecodeEngine; ``max_new`` is
    learned from the first committed engine (the QoS need estimate for
    submissions that leave max_new_tokens to the engine default).  ``tp``
    is the declared tensor-parallel degree: every placement of this
    engine spans that many mesh devices (1 = unsharded), checked against
    the built engine's ``tp_degree``."""

    __slots__ = ("name", "factory", "replicas", "max_new", "tp", "wgen")

    def __init__(self, name, factory, replicas, tp=None):
        self.name = name
        self.factory = factory
        self.replicas = replicas
        self.max_new = 0
        self.tp = tp
        self.wgen = None         # weight generation the spec serves


class _StreamRec:
    """Router-side record of one admitted stream (the session-affinity
    pin).  Guarded by the router's ``_lock``."""

    __slots__ = ("name", "rid", "gen", "tenant", "need_tokens", "wgen")

    def __init__(self, name, rid, gen, tenant, need_tokens, wgen=None):
        self.name = name
        self.rid = rid
        self.gen = gen
        self.tenant = tenant
        self.need_tokens = need_tokens
        # weight generation the stream STARTED on; pinned for life
        # (docs/CONCURRENCY.md invariant 13) — handoffs may move the
        # stream between engines but never across generations
        self.wgen = wgen


class _Tenant:
    """Per-tenant QoS accounting.  Guarded by the router's ``_lock``."""

    __slots__ = ("name", "weight", "token_budget", "inflight_tokens",
                 "admitted", "completed", "ok", "qos_sheds")

    def __init__(self, name, weight=1.0, token_budget=None):
        self.name = name
        self.weight = float(weight)
        self.token_budget = token_budget
        self.inflight_tokens = 0
        self.admitted = 0
        self.completed = 0
        self.ok = 0
        self.qos_sheds = 0


class FleetRouter:
    """Spread models across replicas; route every predict by health.

    ``replica_factory`` builds one replica server (default: ModelServer).
    ``failover_budget`` bounds how many times one client request may be
    re-routed after an UNAVAILABLE/dead replica.  The per-(model, replica)
    breaker knobs mirror ServableModel's.

    Locking: ``_lock`` guards every piece of routing state (replica table,
    specs, placement, breakers, round-robin cursors, the closed flag).  No
    replica server call ever runs under ``_lock`` — predicts, loads and
    warmups are slow and must not serialize routing.  ``_rebalance_mutex``
    serializes rebalance passes (join + death-triggered) and always nests
    OUTSIDE ``_lock``.
    """

    def __init__(self, replicas=0, replica_factory=None, failover_budget=2,
                 breaker_threshold=3, breaker_backoff_ms=50.0,
                 breaker_max_backoff_ms=2000.0):
        if failover_budget < 0:
            raise ValueError("failover_budget must be >= 0")
        self._factory = replica_factory or ModelServer
        self._failover_budget = int(failover_budget)
        self._breaker_threshold = breaker_threshold
        self._breaker_backoff_s = breaker_backoff_ms / 1e3
        self._breaker_max_backoff_s = breaker_max_backoff_ms / 1e3
        self._lock = threading.Lock()
        self._rebalance_mutex = threading.Lock()
        self._replicas = {}     # rid -> _Replica
        self._specs = {}        # name -> _ModelSpec
        self._placement = {}    # name -> [rid, ...] (routable copies)
        self._breakers = {}     # (name, rid) -> CircuitBreaker
        self._rr = {}           # name -> round-robin cursor
        self._next_rid = 0
        self._closed = False
        # -- rolling deployment state (serving/deploy.py; all under _lock)
        # fleet name -> server-side model name: a swapped-in model copy
        # loads under "name@g<gen>" so old and new coexist on one server
        # during the swap; routing reads through this alias
        self._aliases = {}
        # copies flipped out of routing but still finishing their pinned
        # streams / in-flight predicts: dicts with kind/name/rid/wgen and
        # an "eng" (engine entries) or "sname" (model entries)
        self._retiring = []
        self._deploy = {"generation": None, "previous": None,
                        "staging": None, "revert": None,
                        "last_rollback": None}
        self.stats_sink = FleetStats()
        # -- stateful decode tier (all under _lock, same discipline) -----
        self._dspecs = {}       # name -> _EngineSpec
        self._dplacement = {}   # name -> [rid, ...] (routable engines)
        self._dengines = {}     # (name, rid) -> DecodeEngine
        self._dbreakers = {}    # (name, rid) -> CircuitBreaker
        self._streams = {}      # DecodeStream -> _StreamRec (affinity pins)
        self._departed = set()  # streams handed off before their pin landed
        self._tenants = {}      # tenant name -> _Tenant
        self._scaling = {"high": 0.85, "low": 0.15,
                         "scale_out": None, "scale_in": None}
        self.decode_stats = DecodeFleetStats()
        # lease generations fence replica incarnations across drains and
        # kills; its own RLock is never taken under _lock (registrations
        # happen outside, rows cache the granted generation)
        self._leases = MembershipTable(lease_ttl_s=3600.0)
        for _ in range(replicas):
            self.add_replica()

    # -- replica membership ---------------------------------------------
    def add_replica(self, server=None):
        """Join one replica (building it via the factory if not given),
        then rebalance: every under-replicated model is loaded AND warmed
        on it before its placement commits.  Returns the replica id."""
        server = server if server is not None else self._factory()
        with self._lock:
            if self._closed:
                raise MXNetError("fleet is stopped; create a new FleetRouter")
            rid = "r%d" % self._next_rid
            self._next_rid += 1
        gen = self._leases.register(rid).generation
        with self._lock:
            if self._closed:
                raise MXNetError("fleet is stopped; create a new FleetRouter")
            rep = _Replica(rid, server)
            rep.gen = gen
            self._replicas[rid] = rep
        self._rebalance()
        return rid

    def drain(self, rid):
        """Stop admitting requests to ``rid``; in-flight predicts finish
        (the replica's server keeps running) and every live decode stream
        is **handed off**: the replica's engines quiesce, each stream's
        prefix + KV pages are exported, the lease generation bumps (so
        the drained incarnation is fenced out of emitting), and each
        stream resumes on a survivor — or terminates UNAVAILABLE with its
        prefix when no survivor can adopt it.  Idempotent."""
        with self._lock:
            rep = _lookup_replica(self._replicas, rid)
            if rep.state == DEAD:
                raise MXNetError("replica %s is dead" % rid)
            rep.state = DRAINING
            engines = [(name, eng) for (name, r), eng
                       in self._dengines.items() if r == rid]
            # retiring copies on this replica still hold pinned streams of
            # their own generation; they drain through the same protocol
            # (their snapshots only land on same-generation survivors)
            engines += [(e["name"], e["eng"]) for e in self._retiring
                        if e["kind"] == "engine" and e["rid"] == rid]
        if engines:
            self._handoff_decode(rid, engines)

    def enable(self, rid):
        """Undo ``drain``: restore routing to ``rid`` and resume its
        quiesced decode engines (a fresh lease generation was already
        granted at drain time, so re-enabled engines emit with current
        fencing tokens)."""
        with self._lock:
            rep = _lookup_replica(self._replicas, rid)
            if rep.state == DEAD:
                raise MXNetError("replica %s is dead" % rid)
            rep.state = LIVE
            engines = [eng for (name, r), eng in self._dengines.items()
                       if r == rid]
        for eng in engines:
            eng.resume()

    def kill_replica(self, rid):
        """Abrupt replica death (the test/chaos hook): mark DEAD, drop it
        from every placement, stop its server, rebalance in the
        background.  Returns False if it was already dead/unknown."""
        return self._replica_died(rid)

    def remove_replica(self, rid, timeout_s=10.0):
        """Graceful decommission: drain, wait for in-flight requests to
        finish (bounded), then retire the replica and rebalance."""
        self.drain(rid)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if _lookup_replica(self._replicas, rid).inflight == 0:
                    break
            time.sleep(0.005)
        self._replica_died(rid, expected=True)

    def inflight(self, rid):
        with self._lock:
            return _lookup_replica(self._replicas, rid).inflight

    def replicas(self):
        """rid -> state for every replica ever joined (dead ones linger
        for observability)."""
        with self._lock:
            return {rid: rep.state for rid, rep in self._replicas.items()}

    def server(self, rid):
        """The underlying replica server (tests / direct maintenance)."""
        with self._lock:
            return _lookup_replica(self._replicas, rid).server

    # -- model management ------------------------------------------------
    def load_model(self, name, block, input_shapes, replicas=2, **kwargs):
        """Load ``block`` on the ``replicas`` least-loaded live replicas
        (capped at the live count; at least one required).  Each copy is
        warmed before its placement commits, so the model never takes
        traffic on a cold replica.  ``kwargs`` pass through to
        ``ModelServer.load_model`` and are retained for rebalancing."""
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        with self._lock:
            if self._closed:
                raise MXNetError("fleet is stopped; create a new FleetRouter")
            if name in self._specs:
                raise MXNetError("model %r is already loaded in the fleet"
                                 % name)
            if not any(r.state == LIVE for r in self._replicas.values()):
                raise MXNetError("no live replicas; add_replica() first")
            # reserve the name so a racing duplicate load fails fast;
            # placement stays empty until each copy is warm
            self._specs[name] = _ModelSpec(name, block, input_shapes,
                                           int(replicas), dict(kwargs))
            self._placement[name] = []
            self._rr[name] = 0
        try:
            self._rebalance()
        except Exception:
            self.unload_model(name)
            raise
        with self._lock:
            placed = bool(self._placement.get(name))
        if not placed:
            self.unload_model(name)
            raise MXNetError("could not place model %r on any live replica"
                             % name)

    def unload_model(self, name):
        with self._lock:
            if name not in self._specs:
                raise MXNetError("no model %r in the fleet; loaded: %s"
                                 % (name, sorted(self._specs) or "none"))
            del self._specs[name]
            sname = self._aliases.pop(name, name)
            rids = self._placement.pop(name, [])
            self._rr.pop(name, None)
            servers = []
            for rid in rids:
                self._breakers.pop((name, rid), None)
                rep = self._replicas.get(rid)
                if rep is not None and rep.state != DEAD:
                    servers.append(rep.server)
        for server in servers:
            try:
                server.unload(sname)
            except MXNetError:
                pass   # replica raced into teardown; nothing to unload

    def models(self):
        with self._lock:
            return sorted(self._specs)

    # -- stateful decode tier ---------------------------------------------
    def load_decode(self, name, factory, replicas=1, tp=None):
        """Place decode engines for ``name`` on the ``replicas``
        least-loaded live replicas.  ``factory(name)`` must build one
        warmed :class:`~mxnet_tpu.serving.decode.DecodeEngine` (identical
        params per call — the fleet hands streams between copies and the
        merged output must be bitwise-consistent).  Each engine attaches
        to its replica's server, so a replica death tears its engines
        down with it.

        ``tp`` declares the engine's tensor-parallel degree: a tp=k
        engine is mesh-backed (the factory wraps its model in
        ``ShardedDecodeModel(tp=k)``) and consumes k devices per
        placement in ``scaling_advice()``'s footprint accounting.  The
        built engine's ``tp_degree`` must match the declaration —
        mismatch fails the load with an MXNetError naming both.  KV
        headroom needs no tp awareness: the engine reports its logical
        pool once (the pool is head-SHARDED over the mesh, not
        replicated), so summing placements never double-counts shards."""
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        if tp is not None and int(tp) < 1:
            raise ValueError("tp must be >= 1 (or None for unsharded)")
        with self._lock:
            if self._closed:
                raise MXNetError("fleet is stopped; create a new FleetRouter")
            if name in self._dspecs or name in self._specs:
                raise MXNetError("%r is already loaded in the fleet" % name)
            if not any(r.state == LIVE for r in self._replicas.values()):
                raise MXNetError("no live replicas; add_replica() first")
            self._dspecs[name] = _EngineSpec(
                name, factory, int(replicas),
                tp=None if tp is None else int(tp))
            self._dplacement[name] = []
        try:
            self._rebalance()
        except Exception:
            self.unload_decode(name)
            raise
        with self._lock:
            placed = bool(self._dplacement.get(name))
        if not placed:
            self.unload_decode(name)
            raise MXNetError("could not place decode engine %r on any live "
                             "replica" % name)

    def unload_decode(self, name):
        with self._lock:
            if name not in self._dspecs:
                raise MXNetError("no decode engine %r in the fleet; "
                                 "loaded: %s"
                                 % (name, sorted(self._dspecs) or "none"))
            del self._dspecs[name]
            rids = self._dplacement.pop(name, [])
            engines = []
            for rid in rids:
                self._dbreakers.pop((name, rid), None)
                eng = self._dengines.pop((name, rid), None)
                rep = self._replicas.get(rid)
                if eng is not None and rep is not None \
                        and rep.state != DEAD:
                    engines.append((rep.server, eng))
        for server, eng in engines:
            try:
                # by the ENGINE's name: a swapped-in copy attaches under
                # "name@g<gen>", not the fleet name
                server.detach_engine(eng.name)
            except MXNetError:
                pass
            eng.stop()

    def decode_models(self):
        with self._lock:
            return sorted(self._dspecs)

    def engine(self, name, rid):
        """The placed engine object (tests / direct maintenance)."""
        with self._lock:
            eng = self._dengines.get((name, rid))
        if eng is None:
            raise MXNetError("no engine %r on replica %s" % (name, rid))
        return eng

    # mxflow: hot (stream routing path)
    def submit_stream(self, name, prompt, max_new_tokens=None,
                      timeout_ms=None, tenant=None, on_token=None,
                      temperature=0.0, top_k=0, top_p=1.0, seed=None):
        """Admit one generation stream into the fleet; always returns a
        DecodeStream (rejections come back already terminal, same status
        discipline as ``DecodeEngine.submit``).

        Admission is two-gated: the **tenant QoS gate** first (token
        budget + weighted-fair share — an over-budget tenant sheds
        OVERLOADED while others flow), then **KV-aware placement**: the
        stream lands on the LIVE replica whose engine scores best on
        free KV blocks / queue headroom / throughput, with bounded
        failover past UNAVAILABLE engines.  Once admitted, the stream is
        pinned to its placement (session affinity) and every emission is
        fenced by ``(rid, lease_generation)``."""
        from .decode.engine import DecodeStream
        t_deadline = (time.monotonic() + timeout_ms / 1e3
                      if timeout_ms is not None else None)
        tenant = tenant if tenant is not None else "default"
        try:
            plen = len(prompt)
        except TypeError:
            plen = 1
        with self._lock:
            spec = self._dspecs.get(name)
            spec_max_new = spec.max_new if spec is not None else 0
        if spec is None:
            raise MXNetError("no decode engine %r in the fleet; loaded: %s"
                             % (name, sorted(self.decode_models()) or "none"))
        need = int(plen) + int(max_new_tokens if max_new_tokens is not None
                               else spec_max_new)

        def _reject(status, counter, error):
            counter()
            stream = DecodeStream(None, need, t_deadline)
            stream.complete(status, error=error)
            return stream

        # -- QoS gate: capacity signals outside _lock, verdict under it --
        free_tokens, cap_tokens = self._decode_headroom(name)
        with self._lock:
            ten = self._tenants.get(tenant)
            if ten is None:
                ten = _Tenant(tenant)
                self._tenants[tenant] = ten
            total_w = sum(t.weight for t in self._tenants.values())
            fair = (cap_tokens * ten.weight / total_w if total_w > 0
                    else cap_tokens)
            if ten.token_budget is not None \
                    and ten.inflight_tokens + need > ten.token_budget:
                ten.qos_sheds += 1
                verdict = ("tenant %r over token budget (%d in flight + %d "
                           "needed > %d)" % (tenant, ten.inflight_tokens,
                                             need, ten.token_budget))
            elif ten.inflight_tokens + need > fair and free_tokens < need:
                ten.qos_sheds += 1
                verdict = ("tenant %r over its weighted share (%.0f tokens) "
                           "under contention" % (tenant, fair))
            else:
                verdict = None
                ten.inflight_tokens += need
        if verdict is not None:
            return _reject(OVERLOADED, self.decode_stats.on_shed, verdict)

        # -- KV-aware placement with bounded failover --------------------
        def _release_tokens():
            with self._lock:
                t = self._tenants.get(tenant)
                if t is not None:
                    t.inflight_tokens = max(0, t.inflight_tokens - need)

        tried = set()
        stream = None
        reason = "no attempts"
        for attempt in range(self._failover_budget + 1):
            sel, reason = self._select_decode(name, tried)
            if sel is None:
                break
            rep, eng, gen, breaker = sel
            owner = (rep.rid, gen)
            try:
                faults.fault_point("fleet.replica", replica=rep.rid,
                                   model=name)
            except faults.SimulatedCrash:
                # same contract as _route: the crash is the REPLICA's
                # death and this router survives it
                self._replica_died(rep.rid)
                tried.add(rep.rid)
                self.decode_stats.on_failover()
                continue
            s = eng.submit(prompt, max_new_tokens=max_new_tokens,
                           timeout_ms=timeout_ms, on_token=on_token,
                           owner=owner, temperature=temperature,
                           top_k=top_k, top_p=top_p, seed=seed)
            if s.admitted:
                breaker.on_success()
                stream = s
                break
            status = s.snapshot()[0]
            if status == INVALID_INPUT:
                _release_tokens()
                self.decode_stats.on_invalid()
                return s
            if status == UNAVAILABLE:
                breaker.on_failure()
            tried.add(rep.rid)           # OVERLOADED: try a freer replica
            self.decode_stats.on_failover()
        if stream is None:
            _release_tokens()
            return _reject(
                UNAVAILABLE, self.decode_stats.on_unavailable_rejected,
                "no routable decode replica for %r (%s)" % (name, reason))
        # session affinity: pin the stream to wherever it actually lives
        # NOW (a drain may already have re-owned it mid-admission)
        ow = stream.owner()
        rid, gen = ow if (isinstance(ow, tuple) and len(ow) == 2) \
            else (rep.rid, gen)
        with self._lock:
            # the generation pin comes from the ENGINE that admitted: a
            # swap committing between selection and this pin leaves the
            # old engine retiring but still the stream's home, so its tag
            # (not the spec's current one) is the truth
            rec = _StreamRec(name, rid, gen, tenant, need,
                             wgen=eng.generation)
            if stream in self._departed:
                # handed off to another tier before this pin landed: the
                # rec still settles the tenant + terminal accounting, but
                # it must never match a local replica id again
                self._departed.discard(stream)
                rec.rid = rec.gen = None
            self._streams[stream] = rec
            ten = self._tenants.get(tenant)
            if ten is not None:
                ten.admitted += 1
        self.decode_stats.on_admitted()
        # terminal hook AFTER the rec exists: fires immediately if the
        # stream already completed, so the rec can never leak
        stream.on_terminal(self._stream_done)
        return stream

    def _decode_headroom(self, name):
        """(free_tokens, capacity_tokens) across the model's LIVE
        placements — engine signal reads, never under ``_lock``."""
        with self._lock:
            engines = [self._dengines[(name, rid)]
                       for rid in self._dplacement.get(name, ())
                       if (name, rid) in self._dengines
                       and self._replicas[rid].state == LIVE]
        free = cap = 0
        for eng in engines:
            sig = eng.routing_signals()
            free += sig["kv_blocks_free"] * sig["kv_block_size"]
            cap += sig["kv_capacity"] * sig["kv_block_size"]
        return free, cap

    def _select_decode(self, name, tried):
        """Pick (replica, engine, generation, breaker) for one placement
        attempt, or (None, reason).  Candidates are LIVE placements not
        yet tried; the winner maximizes a weighted score over the live
        engine signals — free KV blocks dominate (2x), queue headroom
        next (1x), recent throughput breaks ties (0.25x) — so a new
        stream lands where its KV reservation and queue wait are
        cheapest."""
        with self._lock:
            if self._closed:
                return None, "fleet stopped"
            if name not in self._dspecs:
                raise MXNetError("no decode engine %r in the fleet; "
                                 "loaded: %s"
                                 % (name, sorted(self._dspecs) or "none"))
            placed = list(self._dplacement.get(name, ()))
            if not placed:
                return None, "no replicas host it"
            cands = []
            n_draining = 0
            for rid in placed:
                rep = self._replicas[rid]
                if rep.state == DRAINING:
                    n_draining += 1
                if rid in tried or rep.state != LIVE:
                    continue
                cands.append((rep, self._dengines[(name, rid)], rep.gen,
                              self._dbreakers[(name, rid)]))
        if not cands:
            if n_draining:
                return None, "draining"
            return None, "all replicas tried or dead"
        scored = []
        for rep, eng, gen, breaker in cands:
            # signal reads outside _lock (engine conds are slow-path locks)
            sig = eng.routing_signals()
            if sig["draining"]:
                continue
            scored.append((rep, eng, gen, breaker, sig))
        if not scored:
            return None, "all engines draining"
        max_tps = max(s[4]["tokens_per_s"] for s in scored)

        def score(item):
            sig = item[4]
            kv_free = sig["kv_blocks_free"] / max(1, sig["kv_capacity"])
            queue_room = 1.0 - sig["queue_depth"] / max(1, sig["max_queue"])
            tps = sig["tokens_per_s"] / max_tps if max_tps > 0 else 0.0
            return 2.0 * kv_free + 1.0 * queue_room + 0.25 * tps

        # deterministic order: best score first, rid breaks ties
        scored.sort(key=lambda it: (-score(it), it[0].rid))
        for rep, eng, gen, breaker, _ in scored:
            # admit() outside _lock, same as the predict path
            if breaker.admit() != REJECT:
                return (rep, eng, gen, breaker), None
        return None, "all breakers open"

    def _stream_done(self, stream):
        """Terminal hook for every router-admitted stream: runs off every
        other lock (complete() fires it after releasing the stream cond),
        settles the tenant's in-flight tokens, and counts the terminal
        status exactly once — across however many engines the stream
        visited."""
        status, tokens, ttft, latency, _ = stream.snapshot()
        with self._lock:
            self._departed.discard(stream)
            rec = self._streams.pop(stream, None)
            if rec is None:
                return
            ten = self._tenants.get(rec.tenant)
            if ten is not None:
                ten.inflight_tokens = max(
                    0, ten.inflight_tokens - rec.need_tokens)
                ten.completed += 1
                if status == OK:
                    ten.ok += 1
        self.decode_stats.on_result(status, latency_ms=latency,
                                    ttft_ms=ttft, tokens=len(tokens))

    def _fence_terminate(self, stream, why):
        """Terminate a stream nothing owns anymore: install a fresh
        private fence token (so no engine incarnation can emit past this
        point) and complete UNAVAILABLE with the prefix intact.  Never
        called under ``_lock`` — the terminal hook takes it."""
        token = object()
        stream.set_owner(token)
        if stream.complete(UNAVAILABLE, error=why, owner=token):
            self.decode_stats.on_fenced()

    def _handoff_decode(self, rid, engines):
        """Drain-side stream migration for every engine on ``rid``.

        Protocol (docs/ROBUSTNESS.md "Stream handoff"): (1) **fence** —
        bump the replica's lease generation, so the drained incarnation's
        ``(rid, old_gen)`` tokens go stale the moment anything is
        re-owned; (2) **snapshot** — quiesce each engine at a step
        boundary and export every live stream's prefix + K/V pages;
        (3) **resume** — import each snapshot on the best survivor,
        re-owning the stream to ``(rid2, gen2)`` first.  A wedged engine
        (quiesce timeout) or an exhausted survivor search degrades to a
        fenced UNAVAILABLE terminal — bounded, never a hang."""
        new_gen = self._leases.register(rid).generation
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is not None:
                rep.gen = new_gen
        for name, eng in engines:
            if not eng.quiesce(timeout_s=5.0):
                # wedged mid-step: nothing exportable; fence its streams
                with self._lock:
                    stuck = [s for s, rec in self._streams.items()
                             if rec.rid == rid and rec.name == name]
                for stream in stuck:
                    self._fence_terminate(
                        stream, "replica %s wedged during drain" % rid)
                continue
            for stream, snap in eng.export_streams():
                self._resume_on_survivor(name, stream, snap, exclude=rid)

    def mark_departed(self, stream):
        """Detach a stream from its replica pin WITHOUT dropping its rec:
        the disaggregated router calls this the moment a prefill engine
        hands the stream to the decode tier.  The rec keeps settling the
        tenant tokens and the terminal count (cross-tier conservation
        stays on THIS router), but ``rid``/``gen`` go None so a later
        death or wedged drain of the prefill replica can never fence a
        stream that now lives on the other tier.  If the handoff outraces
        ``submit_stream``'s pin, the stream is parked in ``_departed``
        and the pin lands already-detached."""
        with self._lock:
            rec = self._streams.get(stream)
            if rec is not None:
                rec.rid = rec.gen = None
            else:
                self._departed.add(stream)

    def adopt_stream(self, name, stream, snap, exclude=None):
        """Adopt a stream exported by ANOTHER router (the cross-tier
        entry: serving/disagg/ lands prefill-tier snapshots here).  Same
        protocol as a drain resume — generation check, re-own, import on
        the best-scoring replica with bounded failover.  Returns True on
        adoption (counted in ``decode_stats.handoffs``); False when no
        replica could take it, in which case the stream was already
        fence-terminated UNAVAILABLE with its prefix intact."""
        with self._lock:
            if name not in self._dspecs:
                raise MXNetError("no decode engine %r in the fleet; "
                                 "loaded: %s"
                                 % (name, sorted(self._dspecs) or "none"))
        return self._resume_on_survivor(name, stream, snap, exclude=exclude)

    def _resume_on_survivor(self, name, stream, snap, exclude):
        """Land one exported stream on the best surviving replica; on
        exhaustion, fence-terminate it (UNAVAILABLE, prefix intact).

        Generation routing: a snapshot carries the weight generation of
        the engine that exported it, and it may only resume on an engine
        of the SAME generation (invariant 13; import_stream enforces it
        bitwise too).  A snapshot from the fleet's current generation
        takes the normal scored path; one from a retiring generation can
        only land on a retiring same-generation copy (the already-cut-over
        survivor of the rolling swap)."""
        if stream.snapshot()[0] is not None:
            # terminal while in flight (a concurrent kill fenced it):
            # importing it would strand a stream no engine can complete
            return False
        wgen = snap.get("generation")
        with self._lock:
            spec = self._dspecs.get(name)
            current = spec.wgen if spec is not None else None
        if wgen != current:
            return self._resume_on_retiring(name, stream, snap, wgen,
                                            exclude)
        tried = {exclude}
        for _ in range(self._failover_budget + 1):
            sel, _reason = self._select_decode(name, tried)
            if sel is None:
                break
            rep2, eng2, gen2, _breaker = sel
            try:
                # the fencing handshake: the target's generation must be
                # current (a stale/zombie incarnation fails here), and
                # the stream is re-owned BEFORE the import so the old
                # engine's in-flight emissions are refused from now on
                self._leases.check_generation(rep2.rid, gen2)
            except MXNetError:
                tried.add(rep2.rid)
                continue
            owner2 = (rep2.rid, gen2)
            stream.set_owner(owner2)
            try:
                eng2.import_stream(snap, stream=stream, owner=owner2)
            except MXNetError:
                tried.add(rep2.rid)   # no headroom / draining: next one
                continue
            with self._lock:
                rec = self._streams.get(stream)
                if rec is not None:
                    rec.rid = rep2.rid
                    rec.gen = gen2
            self.decode_stats.on_handoff()
            return True
        self._fence_terminate(
            stream, "drained replica's stream found no survivor with KV "
                    "headroom; re-admit with the emitted prefix as prompt")
        return False

    def _resume_on_retiring(self, name, stream, snap, wgen, exclude):
        """Land a retiring-generation snapshot on a retiring
        same-generation copy; fence-terminate when none survives."""
        with self._lock:
            cands = []
            for entry in self._retiring:
                if (entry["kind"] == "engine" and entry["name"] == name
                        and entry["wgen"] == wgen
                        and entry["rid"] != exclude):
                    rep = self._replicas.get(entry["rid"])
                    if rep is not None and rep.state == LIVE:
                        cands.append((rep, entry["eng"], rep.gen))
        for rep2, eng2, gen2 in cands:
            try:
                self._leases.check_generation(rep2.rid, gen2)
            except MXNetError:
                continue
            owner2 = (rep2.rid, gen2)
            stream.set_owner(owner2)
            try:
                eng2.import_stream(snap, stream=stream, owner=owner2)
            except MXNetError:
                continue      # no headroom / mid-retire: next candidate
            with self._lock:
                rec = self._streams.get(stream)
                if rec is not None:
                    rec.rid = rep2.rid
                    rec.gen = gen2
            self.decode_stats.on_handoff()
            return True
        self._fence_terminate(
            stream, "stream's weight generation %r has no surviving copy; "
                    "re-admit with the emitted prefix as prompt" % (wgen,))
        return False

    # -- multi-tenant QoS -------------------------------------------------
    def set_tenant(self, name, weight=1.0, token_budget=None):
        """Configure one tenant: ``weight`` is its share of the fleet's
        KV token capacity under contention; ``token_budget`` (tokens in
        flight, prompt + budgeted generation) is an absolute cap, None =
        uncapped.  Unknown tenants auto-create at weight 1.0 on first
        submission."""
        if weight <= 0:
            raise ValueError("tenant weight must be > 0")
        with self._lock:
            ten = self._tenants.get(name)
            if ten is None:
                self._tenants[name] = _Tenant(name, weight, token_budget)
            else:
                ten.weight = float(weight)
                ten.token_budget = token_budget

    def tenant_snapshot(self):
        with self._lock:
            return {
                t.name: {
                    "weight": t.weight,
                    "token_budget": t.token_budget,
                    "inflight_tokens": t.inflight_tokens,
                    "admitted": t.admitted,
                    "completed": t.completed,
                    "ok": t.ok,
                    "qos_sheds": t.qos_sheds,
                } for t in self._tenants.values()
            }

    def scale_decode(self, name, replicas):
        """Retarget a decode engine's replica count and converge toward
        it: scale-out builds + warms a fresh engine on a spare replica
        BEFORE its placement commits (the warm-before-cutover rule, via
        ``_rebalance``), so a joining copy never serves cold.  Lowering
        the target removes nothing by itself — scale-in is ``drain(rid)``
        (streams hand off) followed by ``remove_replica(rid)``, with the
        lowered target keeping the rebalancer from re-placing onto the
        survivors.  The autoscaler (serving/disagg/autoscaler.py) drives
        both directions."""
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        with self._lock:
            spec = self._dspecs.get(name)
            if spec is None:
                raise MXNetError("no decode engine %r in the fleet; "
                                 "loaded: %s"
                                 % (name, sorted(self._dspecs) or "none"))
            spec.replicas = int(replicas)
        self._rebalance()

    # -- scaling policy hooks ----------------------------------------------
    def set_scaling_policy(self, scale_out=None, scale_in=None,
                           high=0.85, low=0.15):
        """Install scale-out/scale-in callbacks (``cb(router, advice)``)
        and the KV-utilization / queue-fill thresholds that trigger
        them."""
        if not 0.0 <= low < high <= 1.0:
            raise ValueError("need 0 <= low < high <= 1")
        with self._lock:
            self._scaling = {"high": float(high), "low": float(low),
                             "scale_out": scale_out, "scale_in": scale_in}

    def scaling_advice(self):
        """Derive scale-out/hold/scale-in advice from the live breaker +
        engine signals: sustained KV pressure or queue depth (or an
        unhealthy breaker) says scale out; a near-idle fleet says scale
        in.  The advice also carries the mesh footprint — a tp=k engine
        placement consumes k devices — so policies can see when scale-out
        would overcommit the device budget.

        ``advice["engines"]`` breaks the same signals down per engine
        NAME (replica count, per-name KV utilization / queue fill /
        device footprint, and which thresholds that name tripped) — the
        disaggregated router surfaces these as its per-tier reasons, and
        a policy can scale one engine while holding another."""
        import jax

        devices_total = jax.local_device_count()
        with self._lock:
            engines = list(self._dengines.items())
            breakers = list(self._dbreakers.values())
            high = self._scaling["high"]
            low = self._scaling["low"]
        if not engines:
            return {"action": "hold", "kv_utilization": 0.0,
                    "queue_fill": 0.0, "unhealthy_breakers": 0,
                    "devices_in_use": 0, "devices_total": devices_total,
                    "kv_bytes_free": 0, "kv_bytes_capacity": 0,
                    "engines": {},
                    "reasons": ["no decode engines placed"]}
        utils, fills = [], []
        devices_in_use = 0
        kv_bytes_free = kv_bytes_capacity = 0
        per_name = {}
        for (name, _rid), eng in engines:
            sig = eng.routing_signals()
            cap = max(1, sig["kv_capacity"])
            util = 1.0 - sig["kv_blocks_free"] / cap
            fill = sig["queue_depth"] / max(1, sig["max_queue"])
            devs = max(1, int(sig.get("tp_degree", 1)))
            b_free = int(sig.get("kv_bytes_free", 0))
            b_cap = int(sig.get("kv_bytes_capacity", 0))
            utils.append(util)
            fills.append(fill)
            devices_in_use += devs
            kv_bytes_free += b_free
            kv_bytes_capacity += b_cap
            row = per_name.setdefault(
                name, {"replicas": 0, "devices_in_use": 0,
                       "kv_bytes_free": 0, "kv_bytes_capacity": 0,
                       "_utils": [], "_fills": []})
            row["replicas"] += 1
            row["devices_in_use"] += devs
            row["kv_bytes_free"] += b_free
            row["kv_bytes_capacity"] += b_cap
            row["_utils"].append(util)
            row["_fills"].append(fill)
        breakdown = {}
        for name, row in sorted(per_name.items()):
            n_util = sum(row["_utils"]) / len(row["_utils"])
            n_fill = max(row["_fills"])
            n_reasons = []
            if n_util >= high:
                n_reasons.append("kv utilization %.2f >= %.2f"
                                 % (n_util, high))
            if n_fill >= high:
                n_reasons.append("queue fill %.2f >= %.2f" % (n_fill, high))
            breakdown[name] = {
                "replicas": row["replicas"],
                "devices_in_use": row["devices_in_use"],
                "kv_utilization": n_util,
                "queue_fill": n_fill,
                "kv_bytes_free": row["kv_bytes_free"],
                "kv_bytes_capacity": row["kv_bytes_capacity"],
                "reasons": n_reasons,
            }
        kv_util = sum(utils) / len(utils)
        queue_fill = max(fills)
        unhealthy = sum(1 for b in breakers if b.health() != HEALTHY)
        reasons = []
        if kv_util >= high:
            reasons.append("kv utilization %.2f >= %.2f" % (kv_util, high))
        if queue_fill >= high:
            reasons.append("queue fill %.2f >= %.2f" % (queue_fill, high))
        if unhealthy:
            reasons.append("%d unhealthy engine breaker(s)" % unhealthy)
        if reasons:
            action = "scale_out"
        elif kv_util <= low and queue_fill <= low and not unhealthy:
            action = "scale_in"
            reasons = ["kv utilization %.2f and queue fill %.2f <= %.2f"
                       % (kv_util, queue_fill, low)]
        else:
            action = "hold"
            reasons = ["within thresholds"]
        if action == "scale_out" and devices_in_use >= devices_total:
            reasons.append("device budget exhausted: %d/%d devices in use"
                           % (devices_in_use, devices_total))
        return {"action": action, "kv_utilization": kv_util,
                "queue_fill": queue_fill, "unhealthy_breakers": unhealthy,
                "devices_in_use": devices_in_use,
                "devices_total": devices_total,
                # bytes-based headroom summed from the engines' HBM
                # accountant signals (block geometry x unreserved blocks)
                "kv_bytes_free": kv_bytes_free,
                "kv_bytes_capacity": kv_bytes_capacity,
                "engines": breakdown,
                "reasons": reasons}

    def poll_scaling(self):
        """Evaluate ``scaling_advice()`` and invoke the matching policy
        hook (if installed); returns the advice."""
        advice = self.scaling_advice()
        with self._lock:
            cb = self._scaling.get(advice["action"])
        if cb is not None:
            cb(self, advice)
        return advice

    # -- inference -------------------------------------------------------
    def predict(self, name, data, timeout_ms=None):
        """Blocking fleet predict; always returns an InferenceResult.

        Routes to a healthy replica; an UNAVAILABLE result, an injected
        link fault, or the replica dying mid-request triggers failover to
        the next routable replica, at most ``failover_budget`` times.
        Exactly one terminal status is counted per client call."""
        t0 = time.monotonic()
        res = self._route(name, data, timeout_ms)
        ms = (time.monotonic() - t0) * 1e3
        if res.latency_ms is None:
            res.latency_ms = ms
        self.stats_sink.on_result(res.status, ms)
        return res

    def _route(self, name, data, timeout_ms):
        tried = set()
        budget = self._failover_budget
        for attempt in range(budget + 1):
            sel, reason = self._select(name, tried)
            if sel is None:
                return InferenceResult(
                    UNAVAILABLE,
                    error="no routable replica for %r (%s)" % (name, reason))
            rep, breaker, sname = sel
            self._begin(rep)
            try:
                faults.fault_point("fleet.replica", replica=rep.rid,
                                   model=name)
                res = rep.server.predict(sname, data, timeout_ms=timeout_ms)
            except faults.SimulatedCrash:
                # the ONE place production code catches SimulatedCrash: at
                # the fleet.replica site the crash is the REPLICA's death
                # and this router is the surviving process (faults.py)
                self._replica_died(rep.rid)
                tried.add(rep.rid)
                if attempt < budget:
                    self.stats_sink.on_failover()
                    continue
                return InferenceResult(
                    UNAVAILABLE,
                    error="replica %s died mid-request; failover budget "
                          "exhausted" % rep.rid)
            except faults.InjectedFault as exc:
                # transient/fatal link fault between router and replica:
                # the replica may be fine, but THIS path isn't — count a
                # breaker failure and fail over
                breaker.on_failure()
                tried.add(rep.rid)
                if attempt < budget:
                    self.stats_sink.on_failover()
                    continue
                return InferenceResult(
                    UNAVAILABLE,
                    error="replica %s unreachable (%s); failover budget "
                          "exhausted" % (rep.rid, exc))
            finally:
                self._end(rep)
            if res.status != UNAVAILABLE:
                # the replica answered — reachable from the router's seat.
                # (ERROR/OVERLOADED are the replica's own concern; its
                # per-model breaker and queue bound handle them.)
                breaker.on_success()
                return res
            breaker.on_failure()
            tried.add(rep.rid)
            if attempt < budget:
                self.stats_sink.on_failover()
                continue
            return res
        raise AssertionError("unreachable")   # loop always returns

    def _select(self, name, tried):
        """Pick (replica, breaker, server-side name) for one attempt, or
        (None, reason).  The server-side name is the deployment alias —
        the fleet name itself until a swap commits, "name@g<gen>" after.

        Round-robin over the model's placement, skipping already-tried,
        non-LIVE, and breaker-REJECT replicas.  Unknown model raises."""
        with self._lock:
            if self._closed:
                return None, "fleet stopped"
            if name not in self._specs:
                raise MXNetError("no model %r in the fleet; loaded: %s"
                                 % (name, sorted(self._specs) or "none"))
            sname = self._aliases.get(name, name)
            placed = list(self._placement.get(name, ()))
            if not placed:
                return None, "no replicas host it"
            cursor = self._rr[name]
            self._rr[name] = cursor + 1
            start = cursor % len(placed)
            order = placed[start:] + placed[:start]
            cands = []
            n_draining = 0
            for rid in order:
                rep = self._replicas[rid]
                if rep.state == DRAINING:
                    n_draining += 1
                if rid in tried or rep.state != LIVE:
                    continue
                cands.append((rep, self._breakers[(name, rid)]))
        if not cands:
            if n_draining:
                return None, "draining"
            return None, "all replicas tried or dead"
        for rep, breaker in cands:
            # admit() outside _lock: the breaker has its own lock, and a
            # REJECT here must not stall other routing threads
            if breaker.admit() != REJECT:
                return (rep, breaker, sname), None
        return None, "all breakers open"

    def _begin(self, rep):
        with self._lock:
            rep.inflight += 1

    def _end(self, rep):
        with self._lock:
            rep.inflight -= 1

    # -- replica death + rebalancing --------------------------------------
    def _replica_died(self, rid, expected=False):
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None or rep.state == DEAD:
                return False
            rep.state = DEAD
            # an in-progress swap can no longer cover this replica: the
            # staged copies on it die with the server below, and commit
            # must not flip a partial fleet — mark the staging aborted so
            # commit_swap refuses and the controller aborts back to the
            # old generation (evaluated BEFORE placements are pruned, so
            # "did the dead replica matter to the swap" sees the truth)
            st = self._deploy["staging"]
            if st is not None and st["aborted"] is None:
                involved = rid in st["rids"] or any(
                    rid in self._placement.get(n, ())
                    or rid in self._dplacement.get(n, ())
                    for n in st["names"])
                if involved:
                    st["aborted"] = "replica %s died mid-swap" % rid
                for key in [k for k in st["engines"] if k[1] == rid]:
                    del st["engines"][key]
                for key in [k for k in st["models"] if k[1] == rid]:
                    del st["models"][key]
            # retiring copies on the dead replica are gone with it; their
            # streams are swept with the affected set below
            self._retiring = [e for e in self._retiring
                              if e["rid"] != rid]
            for name, rids in self._placement.items():
                if rid in rids:
                    rids.remove(rid)
                    self._breakers.pop((name, rid), None)
            for name, rids in self._dplacement.items():
                if rid in rids:
                    rids.remove(rid)
            dkeys = [(name, r) for (name, r) in self._dengines if r == rid]
            for key in dkeys:
                self._dengines.pop(key, None)
                self._dbreakers.pop(key, None)
            affected = [s for s, rec in self._streams.items()
                        if rec.rid == rid]
            closed = self._closed
        if not expected:
            self.stats_sink.on_replica_death()
        # fence the dead incarnation: any zombie still holding the old
        # generation fails check_generation on future import attempts
        self._leases.register(rid)
        try:
            rep.server.stop()
        except Exception:
            pass   # it "crashed"; best-effort teardown of the local object
        # the server stop above drained the attached engines: their live
        # streams completed UNAVAILABLE with matching fencing tokens (no
        # snapshot exists in a crash — the prefix is the client's to
        # re-admit).  Sweep any router-registered stream that still isn't
        # terminal (e.g. lost a submit-vs-crash race) with a fence token,
        # so no stream on a dead replica can ever hang.
        for stream in affected:
            if stream.snapshot()[0] is None:
                self._fence_terminate(
                    stream, "replica %s died; re-admit with the emitted "
                            "prefix as prompt" % rid)
        if not closed:
            # rebalance off the request path: the failing request has
            # already failed over to a warm copy; restoring the replication
            # factor (re-warm included) is background work
            threading.Thread(target=self._rebalance,
                             name="fleet-rebalance", daemon=True).start()
        return True

    def _rebalance(self):
        """Restore every model to min(target, live replicas) copies.

        One (model, replica) deficit at a time: pick the least-loaded live
        candidate under ``_lock``, load + warm OUTSIDE the lock, then
        commit the placement — the re-warm-before-cutover rule."""
        with self._rebalance_mutex:
            failed = set()   # (name, rid) that refused the load this pass
            while True:
                task = None
                with self._lock:
                    if self._closed:
                        return
                    live = [r for r in self._replicas.values()
                            if r.state == LIVE]
                    hosted = {r.rid: 0 for r in live}
                    for placement in (self._placement, self._dplacement):
                        for rids in placement.values():
                            for rid in rids:
                                if rid in hosted:
                                    hosted[rid] += 1
                    for name in sorted(self._specs):
                        spec = self._specs[name]
                        placed = self._placement[name]
                        live_placed = [rid for rid in placed
                                       if self._replicas[rid].state == LIVE]
                        want = min(spec.replicas, len(live))
                        if len(live_placed) >= want:
                            continue
                        cands = [r for r in live
                                 if r.rid not in placed
                                 and (name, r.rid) not in failed]
                        if not cands:
                            continue
                        cands.sort(key=lambda r: (hosted[r.rid], r.rid))
                        # alias + weight generation captured with the
                        # task: if a deployment commits while this copy
                        # warms, the commit-time re-check below rolls the
                        # superseded copy back instead of routing it
                        task = (name, spec, cands[0],
                                self._aliases.get(name, name), spec.wgen)
                        break
                    dtask = None
                    if task is None:
                        # decode-engine deficits: same one-per-pass rule,
                        # least-loaded counts BOTH tiers' placements
                        for name in sorted(self._dspecs):
                            spec = self._dspecs[name]
                            placed = self._dplacement[name]
                            live_placed = [
                                rid for rid in placed
                                if self._replicas[rid].state == LIVE]
                            want = min(spec.replicas, len(live))
                            if len(live_placed) >= want:
                                continue
                            cands = [r for r in live
                                     if r.rid not in placed
                                     and (name, r.rid) not in failed]
                            if not cands:
                                continue
                            cands.sort(key=lambda r: (hosted[r.rid], r.rid))
                            dtask = (name, spec, cands[0], spec.wgen)
                            break
                    if task is None and dtask is None:
                        return
                if task is not None:
                    name, spec, rep, sname, wgen0 = task
                    try:
                        # load + full bucket-menu warmup on the new replica,
                        # BEFORE the placement commit below makes it routable
                        rep.server.load_model(sname, spec.block,
                                              spec.input_shapes, **spec.kwargs)
                    except MXNetError:
                        failed.add((name, rep.rid))
                        continue
                    committed = False
                    with self._lock:
                        if (not self._closed and rep.state == LIVE
                                and self._specs.get(name) is spec
                                and spec.wgen == wgen0
                                and self._aliases.get(name, name) == sname
                                and rep.rid not in self._placement[name]):
                            self._placement[name].append(rep.rid)
                            self._breakers[(name, rep.rid)] = CircuitBreaker(
                                failure_threshold=self._breaker_threshold,
                                backoff_s=self._breaker_backoff_s,
                                max_backoff_s=self._breaker_max_backoff_s)
                            committed = True
                    if committed:
                        self.stats_sink.on_rebalance()
                    else:
                        # lost the race (replica died / model unloaded /
                        # generation superseded / fleet stopped while
                        # warming): roll the orphan back
                        try:
                            rep.server.unload(sname)
                        except MXNetError:
                            pass
                    continue
                # decode deficit: build + warm a fresh engine OUTSIDE the
                # lock (factory runs prefill/decode warmup), attach it to
                # the replica's server so replica teardown drains it, then
                # commit the placement
                name, spec, rep, wgen0 = dtask
                try:
                    eng = spec.factory(name)
                except MXNetError:
                    failed.add((name, rep.rid))
                    continue
                built_tp = int(getattr(eng, "tp_degree", 1))
                if spec.tp is not None and built_tp != spec.tp:
                    # a misdeclared degree corrupts the fleet's device
                    # accounting, so fail the load loudly (the factory is
                    # deterministic: the first, synchronous placement in
                    # load_decode() hits this before any background pass)
                    eng.stop()
                    raise MXNetError(
                        "decode engine %r was loaded with tp=%d but its "
                        "factory built an engine with tp_degree=%d; wrap "
                        "the factory's model in ShardedDecodeModel(tp=%d) "
                        "or fix the load_decode(tp=...) declaration"
                        % (name, spec.tp, built_tp, spec.tp))
                try:
                    rep.server.attach_engine(eng)
                except MXNetError:
                    eng.stop()
                    failed.add((name, rep.rid))
                    continue
                committed = False
                with self._lock:
                    if (not self._closed and rep.state == LIVE
                            and self._dspecs.get(name) is spec
                            and spec.wgen == wgen0
                            and rep.rid not in self._dplacement[name]):
                        self._dplacement[name].append(rep.rid)
                        self._dengines[(name, rep.rid)] = eng
                        self._dbreakers[(name, rep.rid)] = CircuitBreaker(
                            failure_threshold=self._breaker_threshold,
                            backoff_s=self._breaker_backoff_s,
                            max_backoff_s=self._breaker_max_backoff_s)
                        spec.max_new = eng.max_new_tokens
                        committed = True
                if committed:
                    self.stats_sink.on_rebalance()
                else:
                    try:
                        rep.server.detach_engine(eng.name)
                    except MXNetError:
                        pass
                    eng.stop()

    def wait_converged(self, timeout_s=10.0, reason_on_timeout=False):
        """Block until every model has min(target, live) routable copies
        (rebalancing settled).  Returns True on convergence; on timeout,
        returns False — or, with ``reason_on_timeout=True``, raises an
        MXNetError naming every (model, replica-deficit) still open, so a
        wedged rebalance (e.g. a factory that never finishes warming)
        surfaces as a diagnosis instead of parking the caller forever."""
        deadline = time.monotonic() + timeout_s
        while True:
            deficits = []
            with self._lock:
                n_live = sum(1 for r in self._replicas.values()
                             if r.state == LIVE)
                for tier, placement in (("model", self._placement),
                                        ("decode", self._dplacement)):
                    specs = self._specs if tier == "model" else self._dspecs
                    for name, spec in sorted(specs.items()):
                        live_placed = [rid for rid in placement[name]
                                       if self._replicas[rid].state == LIVE]
                        want = min(spec.replicas, n_live)
                        if len(live_placed) < want:
                            deficits.append(
                                "%s %r: %d/%d routable copies (placed on %s)"
                                % (tier, name, len(live_placed), want,
                                   live_placed or "nothing"))
            if not deficits:
                return True
            if time.monotonic() >= deadline:
                if reason_on_timeout:
                    raise MXNetError(
                        "fleet did not converge within %.1fs; open "
                        "deficits: %s" % (timeout_s, "; ".join(deficits)))
                return False
            time.sleep(0.005)

    # -- rolling weight swap (serving/deploy.py drives these) --------------
    #
    # The four-phase generation swap (docs/ROBUSTNESS.md "Rolling
    # deployment"): begin -> stage (build + warm every new copy OUTSIDE
    # _lock, old copies still serving) -> fence (lease-generation bump on
    # every staged replica) -> commit (one atomic routing flip under
    # _lock: no server or engine call, no fault point, nothing half-done)
    # -> retire (old copies finish their pinned streams, consolidating
    # onto one same-generation sink, then tear down).  abort_swap undoes a
    # pre-commit swap; rollback_swap inverts a committed one while the
    # revert record (cleared by retire_swap) still holds the old copies.

    def begin_swap(self, generation):
        """Open a staging area for weight generation ``generation``.
        Exactly one swap at a time: raises while another is staging or a
        committed one has not been retired yet."""
        with self._lock:
            if self._closed:
                raise MXNetError("fleet is stopped; create a new FleetRouter")
            if self._deploy["staging"] is not None:
                raise MXNetError(
                    "a swap to generation %r is already staging; abort or "
                    "commit it first"
                    % (self._deploy["staging"]["generation"],))
            if self._deploy["revert"] is not None or self._retiring:
                raise MXNetError(
                    "the previous swap has not been retired; call "
                    "retire_swap() (or rollback_swap()) first")
            self._deploy["staging"] = {
                "generation": generation, "names": set(),
                "engines": {},     # (name, rid) -> warmed DecodeEngine
                "models": {},      # (name, rid) -> server-side model name
                "efactories": {},  # name -> generation engine factory
                "mblocks": {},     # name -> generation block
                "rids": set(), "fenced": False, "aborted": None,
            }

    @staticmethod
    def _staging_ok(st):
        """Validate a staging dict (read by the caller under ``_lock``)."""
        if st is None:
            raise MXNetError("no swap staged; call begin_swap() first")
        if st["aborted"] is not None:
            raise MXNetError("swap to generation %r aborted: %s"
                             % (st["generation"], st["aborted"]))
        return st

    def stage_decode(self, name, rid, factory):
        """Build + warm one new-generation engine for placement
        ``(name, rid)``.  ``factory(srv_name)`` must return a warmed
        DecodeEngine; it runs OUTSIDE ``_lock`` (warmup compiles are
        slow) while the old copy keeps serving.  The engine attaches to
        the replica's server under ``"name@g<generation>"`` so both
        generations coexist until commit."""
        with self._lock:
            st = self._staging_ok(self._deploy["staging"])
            g = st["generation"]
            spec = self._dspecs.get(name)
            if spec is None:
                raise MXNetError("no decode engine %r in the fleet; "
                                 "loaded: %s"
                                 % (name, sorted(self._dspecs) or "none"))
            rep = self._replicas.get(rid)
            if rep is None or rep.state != LIVE \
                    or rid not in self._dplacement.get(name, ()):
                raise MXNetError("(%r, %s) is not a LIVE placement"
                                 % (name, rid))
            if (name, rid) in st["engines"]:
                raise MXNetError("(%r, %s) is already staged" % (name, rid))
        srv_name = "%s@g%s" % (name, g)
        eng = factory(srv_name)
        if getattr(eng, "generation", None) is None:
            eng.generation = g
        built_tp = int(getattr(eng, "tp_degree", 1))
        if spec.tp is not None and built_tp != spec.tp:
            eng.stop()
            raise MXNetError(
                "staged engine %r has tp_degree=%d but the fleet spec "
                "declares tp=%d" % (srv_name, built_tp, spec.tp))
        try:
            rep.server.attach_engine(eng)
        except MXNetError:
            eng.stop()
            raise
        with self._lock:
            ok = (self._deploy["staging"] is st and st["aborted"] is None
                  and not self._closed and rep.state == LIVE
                  and self._dspecs.get(name) is spec)
            if ok:
                st["engines"][(name, rid)] = eng
                st["efactories"][name] = factory
                st["names"].add(name)
                st["rids"].add(rid)
        if not ok:
            # lost a death/abort race while warming: tear the orphan down
            try:
                rep.server.detach_engine(eng.name)
            except MXNetError:
                pass
            eng.stop()
            raise MXNetError("swap staging ended while warming %r on %s"
                             % (name, rid))
        return eng

    def stage_model(self, name, rid, block):
        """Load + warm one new-generation model copy for placement
        ``(name, rid)`` under the alias ``"name@g<generation>"`` (spec
        kwargs are inherited; the generation rides in as the copy's
        tag).  Runs outside ``_lock``, old copy still serving."""
        with self._lock:
            st = self._staging_ok(self._deploy["staging"])
            g = st["generation"]
            spec = self._specs.get(name)
            if spec is None:
                raise MXNetError("no model %r in the fleet; loaded: %s"
                                 % (name, sorted(self._specs) or "none"))
            rep = self._replicas.get(rid)
            if rep is None or rep.state != LIVE \
                    or rid not in self._placement.get(name, ()):
                raise MXNetError("(%r, %s) is not a LIVE placement"
                                 % (name, rid))
            if (name, rid) in st["models"]:
                raise MXNetError("(%r, %s) is already staged" % (name, rid))
            kwargs = dict(spec.kwargs)
        kwargs["generation"] = g
        sname = "%s@g%s" % (name, g)
        rep.server.load_model(sname, block, spec.input_shapes, **kwargs)
        with self._lock:
            ok = (self._deploy["staging"] is st and st["aborted"] is None
                  and not self._closed and rep.state == LIVE
                  and self._specs.get(name) is spec)
            if ok:
                st["models"][(name, rid)] = sname
                st["mblocks"][name] = block
                st["names"].add(name)
                st["rids"].add(rid)
        if not ok:
            try:
                rep.server.unload(sname)
            except MXNetError:
                pass
            raise MXNetError("swap staging ended while warming %r on %s"
                             % (name, rid))

    def fence_swap(self):
        """Fence every staged replica's old incarnation: bump its lease
        generation (MembershipTable) and cache the new one on the
        replica row.  In-flight streams keep their per-stream owner
        tokens and keep emitting on the old copies; what dies is the old
        generation's power to RE-own or import anything from here on."""
        with self._lock:
            st = self._staging_ok(self._deploy["staging"])
            if not st["engines"] and not st["models"]:
                raise MXNetError("nothing staged; stage_decode()/"
                                 "stage_model() before fence_swap()")
            rids = sorted(st["rids"])
        for rid in rids:
            new_gen = self._leases.register(rid).generation
            with self._lock:
                rep = self._replicas.get(rid)
                if rep is not None and rep.state != DEAD:
                    rep.gen = new_gen
        with self._lock:
            st2 = self._deploy["staging"]
            if st2 is st:
                st["fenced"] = True

    def commit_swap(self):
        """The atomic routing flip.  Entirely under ``_lock`` with no
        server/engine call and no fault point inside: a kill before it
        leaves the fleet fully on the old generation, a kill after it
        fully on the new one — there is no in-between to observe.

        Requires a fenced, unaborted staging whose copies cover EVERY
        live routable placement of every swapped name (a mid-swap
        replica death breaks coverage and fails the commit).  Old copies
        move to the retiring list; the revert record for
        ``rollback_swap`` is built from the same entries."""
        with self._lock:
            st = self._staging_ok(self._deploy["staging"])
            if not st["fenced"]:
                raise MXNetError("fence_swap() must run before "
                                 "commit_swap()")
            g = st["generation"]
            missing = []
            for name in sorted(st["names"]):
                if name in self._dspecs:
                    for rid in self._dplacement.get(name, ()):
                        if self._replicas[rid].state != DEAD \
                                and (name, rid) not in st["engines"]:
                            missing.append("engine (%s, %s)" % (name, rid))
                if name in self._specs:
                    for rid in self._placement.get(name, ()):
                        if self._replicas[rid].state != DEAD \
                                and (name, rid) not in st["models"]:
                            missing.append("model (%s, %s)" % (name, rid))
            if missing:
                raise MXNetError(
                    "cannot commit generation %r: unstaged live "
                    "placements: %s" % (g, ", ".join(missing)))
            retired = []
            revert = {"generation": self._deploy["generation"],
                      "previous": self._deploy["previous"],
                      "engines": {}, "models": {}, "retired": retired}
            for name in sorted({n for (n, _r) in st["engines"]}):
                spec = self._dspecs[name]
                revert["engines"][name] = {
                    "factory": spec.factory, "wgen": spec.wgen,
                    "max_new": spec.max_new}
                for rid in list(self._dplacement.get(name, ())):
                    key = (name, rid)
                    new_eng = st["engines"].get(key)
                    if new_eng is None:
                        continue   # dead rid already pruned from placement
                    entry = {"kind": "engine", "name": name, "rid": rid,
                             "wgen": spec.wgen,
                             "eng": self._dengines.get(key)}
                    self._retiring.append(entry)
                    retired.append(entry)
                    self._dengines[key] = new_eng
                    breaker = self._dbreakers.get(key)
                    if breaker is not None:
                        breaker.reset()
                    spec.max_new = new_eng.max_new_tokens
                spec.factory = st["efactories"][name]
                spec.wgen = g
            for name in sorted({n for (n, _r) in st["models"]}):
                spec = self._specs[name]
                old_sname = self._aliases.get(name, name)
                revert["models"][name] = {
                    "sname": old_sname, "block": spec.block,
                    "kwargs": spec.kwargs, "wgen": spec.wgen}
                new_sname = "%s@g%s" % (name, g)
                for rid in list(self._placement.get(name, ())):
                    if (name, rid) not in st["models"]:
                        continue
                    entry = {"kind": "model", "name": name, "rid": rid,
                             "wgen": spec.wgen, "sname": old_sname}
                    self._retiring.append(entry)
                    retired.append(entry)
                    breaker = self._breakers.get((name, rid))
                    if breaker is not None:
                        breaker.reset()
                self._aliases[name] = new_sname
                spec.block = st["mblocks"][name]
                kwargs = dict(spec.kwargs)
                kwargs["generation"] = g
                spec.kwargs = kwargs
                spec.wgen = g
            self._deploy["previous"] = self._deploy["generation"]
            self._deploy["generation"] = g
            self._deploy["revert"] = revert
            self._deploy["staging"] = None

    def rollback_swap(self, reason="health gate"):
        """Invert a committed, not-yet-retired swap: the routing flip runs
        backwards under ``_lock`` (old copies come straight back out of
        the retiring list — they were never torn down), the bad
        generation's copies go INTO the retiring list to finish whatever
        streams they admitted, and placements that only ever existed on
        the bad generation (a post-commit rebalance) are dropped for the
        background rebalancer to rebuild from the restored spec."""
        with self._lock:
            revert = self._deploy["revert"]
            if revert is None:
                raise MXNetError("nothing to roll back (no committed, "
                                 "unretired swap)")
            bad_gen = self._deploy["generation"]
            alive = {id(e) for e in self._retiring}
            live_old = {(e["kind"], e["name"], e["rid"]): e
                        for e in revert["retired"] if id(e) in alive}
            for name, saved in revert["engines"].items():
                spec = self._dspecs.get(name)
                if spec is None:
                    continue
                keep = []
                for rid in list(self._dplacement.get(name, ())):
                    key = (name, rid)
                    bad_eng = self._dengines.get(key)
                    if bad_eng is not None:
                        self._retiring.append(
                            {"kind": "engine", "name": name, "rid": rid,
                             "wgen": spec.wgen, "eng": bad_eng})
                    old = live_old.get(("engine", name, rid))
                    if old is not None:
                        self._retiring = [e for e in self._retiring
                                          if e is not old]
                        self._dengines[key] = old["eng"]
                        breaker = self._dbreakers.get(key)
                        if breaker is not None:
                            breaker.reset()
                        keep.append(rid)
                    else:
                        self._dengines.pop(key, None)
                        self._dbreakers.pop(key, None)
                self._dplacement[name] = keep
                spec.factory = saved["factory"]
                spec.wgen = saved["wgen"]
                spec.max_new = saved["max_new"]
            for name, saved in revert["models"].items():
                spec = self._specs.get(name)
                if spec is None:
                    continue
                bad_sname = self._aliases.get(name, name)
                keep = []
                for rid in list(self._placement.get(name, ())):
                    self._retiring.append(
                        {"kind": "model", "name": name, "rid": rid,
                         "wgen": spec.wgen, "sname": bad_sname})
                    old = live_old.get(("model", name, rid))
                    if old is not None:
                        self._retiring = [e for e in self._retiring
                                          if e is not old]
                        breaker = self._breakers.get((name, rid))
                        if breaker is not None:
                            breaker.reset()
                        keep.append(rid)
                    else:
                        self._breakers.pop((name, rid), None)
                self._placement[name] = keep
                if saved["sname"] == name:
                    self._aliases.pop(name, None)
                else:
                    self._aliases[name] = saved["sname"]
                spec.block = saved["block"]
                spec.kwargs = saved["kwargs"]
                spec.wgen = saved["wgen"]
            self._deploy["generation"] = revert["generation"]
            self._deploy["previous"] = revert["previous"]
            self._deploy["last_rollback"] = {"generation": bad_gen,
                                             "reason": reason}
            self._deploy["revert"] = None
            closed = self._closed
        if not closed:
            # rebuild any placement the rollback dropped, off this thread
            threading.Thread(target=self._rebalance,
                             name="fleet-rebalance", daemon=True).start()

    def abort_swap(self, reason=None):
        """Discard a pre-commit staging: staged copies detach/unload and
        stop; routing never changed, so the fleet simply continues on the
        old generation.  Idempotent (no staging = no-op)."""
        with self._lock:
            st = self._deploy["staging"]
            self._deploy["staging"] = None
            work = []
            if st is not None:
                for (name, rid), eng in st["engines"].items():
                    rep = self._replicas.get(rid)
                    if rep is not None and rep.state != DEAD:
                        work.append(("engine", rep.server, eng))
                for (name, rid), sname in st["models"].items():
                    rep = self._replicas.get(rid)
                    if rep is not None and rep.state != DEAD:
                        work.append(("model", rep.server, sname))
        for kind, server, obj in work:
            if kind == "engine":
                try:
                    server.detach_engine(obj.name)
                except MXNetError:
                    pass
                obj.stop()
            else:
                try:
                    server.unload(obj)
                except MXNetError:
                    pass
        return st is not None

    def retire_swap(self, timeout_s=10.0):
        """Finish and tear down every retiring copy; clears the revert
        record (the swap's point of no return — rollback_swap is
        impossible after this returns).

        Retiring engines of one (name, generation) group consolidate
        before teardown: all but one quiesce and fenced-handoff their
        still-running streams onto the group's surviving sink (the
        already-cut-over survivor), which then finishes them — bounded by
        ``timeout_s``, after which leftovers fence-terminate UNAVAILABLE
        with their prefix intact.  Retiring model copies unload once
        their replica's in-flight predicts clear (bounded the same
        way)."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            entries = list(self._retiring)
        groups = {}
        model_entries = []
        for e in entries:
            if e["kind"] == "engine":
                groups.setdefault((e["name"], e["wgen"]), []).append(e)
            else:
                model_entries.append(e)
        handed = fenced = 0

        def _teardown(entry):
            with self._lock:
                present = any(x is entry for x in self._retiring)
                self._retiring = [x for x in self._retiring
                                  if x is not entry]
                rep = self._replicas.get(entry["rid"])
                server = (rep.server if rep is not None
                          and rep.state != DEAD else None)
            if not present or server is None:
                return
            eng = entry["eng"]
            try:
                server.detach_engine(eng.name)
            except MXNetError:
                pass
            eng.stop()

        def _fence_left(name, wgen, rid=None):
            n = 0
            with self._lock:
                stuck = [s for s, rec in self._streams.items()
                         if rec.name == name and rec.wgen == wgen
                         and (rid is None or rec.rid == rid)]
            for stream in stuck:
                self._fence_terminate(
                    stream, "weight generation %r retired before the "
                            "stream finished; re-admit with the emitted "
                            "prefix as prompt" % (wgen,))
                n += 1
            return n

        for (name, wgen), group in sorted(
                groups.items(), key=lambda kv: (kv[0][0], str(kv[0][1]))):
            with self._lock:
                alive = {id(e) for e in self._retiring}
                live = [e for e in group if id(e) in alive
                        and (rep := self._replicas.get(e["rid"]))
                        is not None and rep.state == LIVE]
            sink = live[-1] if live else None
            for e in group:
                if e is sink:
                    continue
                with self._lock:
                    if not any(x is e for x in self._retiring):
                        continue   # swept by a concurrent replica death
                eng = e["eng"]
                if sink is not None and eng.quiesce(timeout_s=5.0):
                    for stream, snap in eng.export_streams():
                        if self._resume_on_retiring(name, stream, snap,
                                                    wgen, exclude=e["rid"]):
                            handed += 1
                        else:
                            fenced += 1
                else:
                    fenced += _fence_left(name, wgen, rid=e["rid"])
                _teardown(e)
            if sink is None:
                fenced += _fence_left(name, wgen)
                continue
            while time.monotonic() < deadline:
                with self._lock:
                    left = any(rec.name == name and rec.wgen == wgen
                               for rec in self._streams.values())
                if not left:
                    break
                time.sleep(0.01)
            fenced += _fence_left(name, wgen)
            _teardown(sink)
        for e in model_entries:
            with self._lock:
                present = any(x is e for x in self._retiring)
                self._retiring = [x for x in self._retiring if x is not e]
                rep = self._replicas.get(e["rid"])
                server = (rep.server if rep is not None
                          and rep.state != DEAD else None)
            if not present or server is None:
                continue
            while time.monotonic() < deadline:
                with self._lock:
                    inflight = rep.inflight
                if inflight == 0:
                    break
                time.sleep(0.005)
            try:
                server.unload(e["sname"])
            except MXNetError:
                pass
        with self._lock:
            self._deploy["revert"] = None
        return {"handoffs": handed, "fenced": fenced,
                "retired": len(entries)}

    # -- observability ----------------------------------------------------
    def health(self, name=None):
        """HEALTHY / DEGRADED / UNAVAILABLE for one model or decode
        engine (or the worst across the fleet).  A name with zero
        routable replicas is UNAVAILABLE; under target, a non-LIVE
        placement, or any breaker off HEALTHY is DEGRADED.  Decode names
        fall through to the attached engines on every placement, so a
        replica whose engine breaker opened degrades the fleet answer
        even before the router's own breaker notices."""
        with self._lock:
            if name is not None and name not in self._specs \
                    and name not in self._dspecs:
                raise MXNetError(
                    "no model %r in the fleet; loaded: %s"
                    % (name, sorted(set(self._specs) | set(self._dspecs))
                       or "none"))
            names = ([name] if name is not None
                     else sorted(set(self._specs) | set(self._dspecs)))
            n_live = sum(1 for r in self._replicas.values()
                         if r.state == LIVE)
            rows = []
            for n in names:
                if n in self._specs:
                    placed = list(self._placement[n])
                    target = self._specs[n].replicas
                    probes = [self._breakers[(n, rid)] for rid in placed
                              if self._replicas[rid].state == LIVE]
                else:
                    placed = list(self._dplacement[n])
                    target = self._dspecs[n].replicas
                    # breaker AND engine per live placement: the engine's
                    # own health (its internal execute breaker) rolls up
                    probes = []
                    for rid in placed:
                        if self._replicas[rid].state != LIVE:
                            continue
                        probes.append(self._dbreakers[(n, rid)])
                        probes.append(self._dengines[(n, rid)])
                states = [self._replicas[rid].state for rid in placed]
                rows.append((target, states, probes))
        worst = HEALTHY
        for target, states, probes in rows:
            n_routable = sum(1 for s in states if s == LIVE)
            if n_routable == 0:
                h = UNAVAILABLE_HEALTH
            else:
                # .health() calls outside _lock (breakers and engines
                # take their own locks)
                levels = [p.health() for p in probes]
                if (worst_health(levels) != HEALTHY
                        or n_routable < min(target, max(n_live, 1))
                        or any(s != LIVE for s in states)):
                    h = DEGRADED
                else:
                    h = HEALTHY
            worst = worst_health((worst, h))
        return worst

    def stats(self):
        """Fleet counters + per-replica and per-model routing state."""
        with self._lock:
            reps = {rid: {"state": rep.state, "inflight": rep.inflight,
                          "models": sorted(n for n, rids
                                           in self._placement.items()
                                           if rid in rids),
                          "engines": sorted(n for n, rids
                                            in self._dplacement.items()
                                            if rid in rids)}
                    for rid, rep in self._replicas.items()}
            models = {}
            for name, spec in self._specs.items():
                placed = list(self._placement[name])
                models[name] = {
                    "target": spec.replicas,
                    "placement": placed,
                    "breakers": {rid: self._breakers[(name, rid)]
                                 for rid in placed
                                 if (name, rid) in self._breakers},
                }
            dmodels = {}
            for name, spec in self._dspecs.items():
                placed = list(self._dplacement[name])
                dmodels[name] = {
                    "target": spec.replicas,
                    "placement": placed,
                    "breakers": {rid: self._dbreakers[(name, rid)]
                                 for rid in placed
                                 if (name, rid) in self._dbreakers},
                }
            dengines = dict(self._dengines)
        for snaps in (models, dmodels):
            for snap in snaps.values():
                snap["breakers"] = {rid: b.snapshot()
                                    for rid, b in snap["breakers"].items()}
        out = self.stats_sink.snapshot()
        out["replicas"] = reps
        out["models"] = models
        out["decode_models"] = dmodels
        # per-engine fall-through: the full DecodeEngine snapshot of every
        # placement, fleet-wide (engine calls outside _lock)
        engines_out = {}
        for (name, rid), eng in sorted(dengines.items()):
            engines_out.setdefault(name, {})[rid] = eng.stats_snapshot()
        out["engines"] = engines_out
        out["decode"] = self.decode_stats.snapshot()
        # fleet-wide prefix-cache / speculation rollup (headroom math
        # already counts shared pages once via each engine's
        # available_unreserved signal)
        roll = {"prefix_hits": 0, "prefix_blocks_shared": 0,
                "cow_forks": 0, "spec_proposed": 0, "spec_accepted": 0}
        for per_model in engines_out.values():
            for snap in per_model.values():
                for key in roll:
                    roll[key] += snap.get(key, 0)
        out["decode"]["prefix_spec"] = roll
        out["tenants"] = self.tenant_snapshot()
        with self._lock:
            st = self._deploy["staging"]
            out["deploy"] = {
                "generation": self._deploy["generation"],
                "previous": self._deploy["previous"],
                "in_progress": None if st is None else {
                    "generation": st["generation"],
                    "staged_engines": sorted(
                        "%s@%s" % k for k in st["engines"]),
                    "staged_models": sorted(
                        "%s@%s" % k for k in st["models"]),
                    "fenced": st["fenced"],
                    "aborted": st["aborted"],
                },
                "retiring": len(self._retiring),
                "aliases": {n: a for n, a in self._aliases.items()
                            if a != n},
                "last_rollback": self._deploy["last_rollback"],
            }
        return out

    # -- lifecycle ---------------------------------------------------------
    def stop(self):
        """Stop every replica server; idempotent."""
        with self._lock:
            self._closed = True
            servers = [rep.server for rep in self._replicas.values()
                       if rep.state != DEAD]
        for server in servers:
            try:
                server.stop()
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

def _lookup_replica(replicas, rid):
    """Row lookup over an already-locked replica table."""
    try:
        return replicas[rid]
    except KeyError:
        raise MXNetError("no replica %r; known: %s"
                         % (rid, sorted(replicas) or "none"))
