"""Elastic serving fleet: health-routed predicts across N server replicas.

One :class:`~mxnet_tpu.serving.server.ModelServer` is one *replica*; this
router is the tier above it — the dynamic-membership story of the TensorFlow
paper (replicas come and go; the system reroutes, drains, and resumes) made
concrete for the serving path:

* **Placement** — ``load_model(name, ..., replicas=k)`` spreads the model
  over the k least-loaded live replicas; every copy is warmed (the full
  bucket-menu precompile) before it takes traffic.
* **Health-routed selection** — one ``serving/health.py`` CircuitBreaker per
  (model, replica) pair, fed by what the *router* observes: an UNAVAILABLE
  result or an unreachable/dead replica is a failure, any answered request
  is a success.  Selection rotates round-robin over the model's placement,
  skipping DRAINING/DEAD replicas and open breakers.
* **Bounded failover** — a predict that lands on a dead or UNAVAILABLE
  replica is retried on the next routable one, at most ``failover_budget``
  times; the request reaches exactly one terminal status either way, so
  fleet conservation (``requests == ok + timeouts + errors + unavailable``)
  holds across failovers.
* **Drain** — ``drain(rid)`` stops admission to a replica while its
  in-flight requests finish (the replica's server keeps running); new
  submissions that have nowhere else to go get UNAVAILABLE with a
  ``draining`` reason.  ``enable(rid)`` restores routing.
* **Rebalance** — when a replica joins (``add_replica``) or dies, every
  under-replicated model is re-loaded — *and re-warmed* — on a new replica
  BEFORE the placement cutover, so failover never recompiles in the hot
  path.  Death-triggered rebalancing runs on a background thread; the dying
  request has already failed over to an existing warm copy.

Replica death is observed, not announced: a ``faults.SimulatedCrash``
injected at the ``fleet.replica`` site (or an explicit ``kill_replica``)
models the replica process dying mid-request.  This is the one site where
production code catches SimulatedCrash — the router IS the surviving
process (see faults.py).

The ``fleet`` mxstress scenario (analysis/schedule.py) is the standing
chaos consumer: a replica is killed under storm load and zero requests may
drop, tail latency stays bounded, and the router must re-converge HEALTHY.
See docs/ROBUSTNESS.md ("Fleet membership") and docs/SERVING.md (topology).
"""
from __future__ import annotations

import threading
import time

from .. import faults
from ..base import MXNetError
from .health import (CircuitBreaker, HEALTHY, DEGRADED, UNAVAILABLE_HEALTH,
                     REJECT)
from .server import (ModelServer, InferenceResult,
                     OK, TIMEOUT, ERROR, UNAVAILABLE, OVERLOADED,
                     INVALID_INPUT)
from .stats import LatencyWindow

__all__ = ["FleetRouter", "FleetStats", "LIVE", "DRAINING", "DEAD"]

# replica lifecycle states
LIVE = "LIVE"          # routable
DRAINING = "DRAINING"  # no new admissions; in-flight requests finish
DEAD = "DEAD"          # crashed or removed; never routable again


class FleetStats:
    """Fleet-level counters.  Thread-safe; same two-tier split as
    ModelStats: ``requests`` counts routed client calls that reached a
    terminal OK/TIMEOUT/ERROR/UNAVAILABLE status (the conservation set);
    ``shed``/``invalid`` count pass-through fast rejections outside it."""

    def __init__(self):
        self._lock = threading.Lock()
        self.requests = 0
        self.ok = 0
        self.timeouts = 0
        self.errors = 0
        self.unavailable = 0
        self.shed = 0            # OVERLOADED passed through from a replica
        self.invalid = 0         # INVALID_INPUT passed through
        self.failovers = 0       # attempts re-routed to another replica
        self.replica_deaths = 0
        self.rebalances = 0      # placement commits after a re-warm
        self._lat = LatencyWindow()

    def on_result(self, status, latency_ms=None):
        with self._lock:
            if status == OK:
                self.requests += 1
                self.ok += 1
            elif status == TIMEOUT:
                self.requests += 1
                self.timeouts += 1
            elif status == ERROR:
                self.requests += 1
                self.errors += 1
            elif status == UNAVAILABLE:
                self.requests += 1
                self.unavailable += 1
            elif status == OVERLOADED:
                self.shed += 1
            elif status == INVALID_INPUT:
                self.invalid += 1
            if latency_ms is not None:
                self._lat.add(latency_ms)

    def on_failover(self):
        with self._lock:
            self.failovers += 1

    def on_replica_death(self):
        with self._lock:
            self.replica_deaths += 1

    def on_rebalance(self):
        with self._lock:
            self.rebalances += 1

    def snapshot(self):
        with self._lock:
            return {
                "requests": self.requests,
                "ok": self.ok,
                "timeouts": self.timeouts,
                "errors": self.errors,
                "unavailable": self.unavailable,
                "shed": self.shed,
                "invalid": self.invalid,
                "failovers": self.failovers,
                "replica_deaths": self.replica_deaths,
                "rebalances": self.rebalances,
                "latency_ms": self._lat.percentiles(),
            }


class _Replica:
    """One replica row; every field except ``server`` is guarded by the
    router's ``_lock`` (``server`` is assigned once and never rebound)."""

    __slots__ = ("rid", "server", "state", "inflight")

    def __init__(self, rid, server):
        self.rid = rid
        self.server = server
        self.state = LIVE
        self.inflight = 0


class _ModelSpec:
    """Everything needed to re-load a model on a joining replica."""

    __slots__ = ("name", "block", "input_shapes", "replicas", "kwargs")

    def __init__(self, name, block, input_shapes, replicas, kwargs):
        self.name = name
        self.block = block
        self.input_shapes = input_shapes
        self.replicas = replicas
        self.kwargs = kwargs


class FleetRouter:
    """Spread models across replicas; route every predict by health.

    ``replica_factory`` builds one replica server (default: ModelServer).
    ``failover_budget`` bounds how many times one client request may be
    re-routed after an UNAVAILABLE/dead replica.  The per-(model, replica)
    breaker knobs mirror ServableModel's.

    Locking: ``_lock`` guards every piece of routing state (replica table,
    specs, placement, breakers, round-robin cursors, the closed flag).  No
    replica server call ever runs under ``_lock`` — predicts, loads and
    warmups are slow and must not serialize routing.  ``_rebalance_mutex``
    serializes rebalance passes (join + death-triggered) and always nests
    OUTSIDE ``_lock``.
    """

    def __init__(self, replicas=0, replica_factory=None, failover_budget=2,
                 breaker_threshold=3, breaker_backoff_ms=50.0,
                 breaker_max_backoff_ms=2000.0):
        if failover_budget < 0:
            raise ValueError("failover_budget must be >= 0")
        self._factory = replica_factory or ModelServer
        self._failover_budget = int(failover_budget)
        self._breaker_threshold = breaker_threshold
        self._breaker_backoff_s = breaker_backoff_ms / 1e3
        self._breaker_max_backoff_s = breaker_max_backoff_ms / 1e3
        self._lock = threading.Lock()
        self._rebalance_mutex = threading.Lock()
        self._replicas = {}     # rid -> _Replica
        self._specs = {}        # name -> _ModelSpec
        self._placement = {}    # name -> [rid, ...] (routable copies)
        self._breakers = {}     # (name, rid) -> CircuitBreaker
        self._rr = {}           # name -> round-robin cursor
        self._next_rid = 0
        self._closed = False
        self.stats_sink = FleetStats()
        for _ in range(replicas):
            self.add_replica()

    # -- replica membership ---------------------------------------------
    def add_replica(self, server=None):
        """Join one replica (building it via the factory if not given),
        then rebalance: every under-replicated model is loaded AND warmed
        on it before its placement commits.  Returns the replica id."""
        server = server if server is not None else self._factory()
        with self._lock:
            if self._closed:
                raise MXNetError("fleet is stopped; create a new FleetRouter")
            rid = "r%d" % self._next_rid
            self._next_rid += 1
            self._replicas[rid] = _Replica(rid, server)
        self._rebalance()
        return rid

    def drain(self, rid):
        """Stop admitting requests to ``rid``; in-flight requests finish
        (the replica's server keeps running).  Idempotent."""
        with self._lock:
            rep = _lookup_replica(self._replicas, rid)
            if rep.state == DEAD:
                raise MXNetError("replica %s is dead" % rid)
            rep.state = DRAINING

    def enable(self, rid):
        """Undo ``drain``: restore routing to ``rid``."""
        with self._lock:
            rep = _lookup_replica(self._replicas, rid)
            if rep.state == DEAD:
                raise MXNetError("replica %s is dead" % rid)
            rep.state = LIVE

    def kill_replica(self, rid):
        """Abrupt replica death (the test/chaos hook): mark DEAD, drop it
        from every placement, stop its server, rebalance in the
        background.  Returns False if it was already dead/unknown."""
        return self._replica_died(rid)

    def remove_replica(self, rid, timeout_s=10.0):
        """Graceful decommission: drain, wait for in-flight requests to
        finish (bounded), then retire the replica and rebalance."""
        self.drain(rid)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if _lookup_replica(self._replicas, rid).inflight == 0:
                    break
            time.sleep(0.005)
        self._replica_died(rid, expected=True)

    def inflight(self, rid):
        with self._lock:
            return _lookup_replica(self._replicas, rid).inflight

    def replicas(self):
        """rid -> state for every replica ever joined (dead ones linger
        for observability)."""
        with self._lock:
            return {rid: rep.state for rid, rep in self._replicas.items()}

    def server(self, rid):
        """The underlying replica server (tests / direct maintenance)."""
        with self._lock:
            return _lookup_replica(self._replicas, rid).server

    # -- model management ------------------------------------------------
    def load_model(self, name, block, input_shapes, replicas=2, **kwargs):
        """Load ``block`` on the ``replicas`` least-loaded live replicas
        (capped at the live count; at least one required).  Each copy is
        warmed before its placement commits, so the model never takes
        traffic on a cold replica.  ``kwargs`` pass through to
        ``ModelServer.load_model`` and are retained for rebalancing."""
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        with self._lock:
            if self._closed:
                raise MXNetError("fleet is stopped; create a new FleetRouter")
            if name in self._specs:
                raise MXNetError("model %r is already loaded in the fleet"
                                 % name)
            if not any(r.state == LIVE for r in self._replicas.values()):
                raise MXNetError("no live replicas; add_replica() first")
            # reserve the name so a racing duplicate load fails fast;
            # placement stays empty until each copy is warm
            self._specs[name] = _ModelSpec(name, block, input_shapes,
                                           int(replicas), dict(kwargs))
            self._placement[name] = []
            self._rr[name] = 0
        try:
            self._rebalance()
        except Exception:
            self.unload_model(name)
            raise
        with self._lock:
            placed = bool(self._placement.get(name))
        if not placed:
            self.unload_model(name)
            raise MXNetError("could not place model %r on any live replica"
                             % name)

    def unload_model(self, name):
        with self._lock:
            if name not in self._specs:
                raise MXNetError("no model %r in the fleet; loaded: %s"
                                 % (name, sorted(self._specs) or "none"))
            del self._specs[name]
            rids = self._placement.pop(name, [])
            self._rr.pop(name, None)
            servers = []
            for rid in rids:
                self._breakers.pop((name, rid), None)
                rep = self._replicas.get(rid)
                if rep is not None and rep.state != DEAD:
                    servers.append(rep.server)
        for server in servers:
            try:
                server.unload(name)
            except MXNetError:
                pass   # replica raced into teardown; nothing to unload

    def models(self):
        with self._lock:
            return sorted(self._specs)

    # -- inference -------------------------------------------------------
    def predict(self, name, data, timeout_ms=None):
        """Blocking fleet predict; always returns an InferenceResult.

        Routes to a healthy replica; an UNAVAILABLE result, an injected
        link fault, or the replica dying mid-request triggers failover to
        the next routable replica, at most ``failover_budget`` times.
        Exactly one terminal status is counted per client call."""
        t0 = time.monotonic()
        res = self._route(name, data, timeout_ms)
        ms = (time.monotonic() - t0) * 1e3
        if res.latency_ms is None:
            res.latency_ms = ms
        self.stats_sink.on_result(res.status, ms)
        return res

    def _route(self, name, data, timeout_ms):
        tried = set()
        budget = self._failover_budget
        for attempt in range(budget + 1):
            sel, reason = self._select(name, tried)
            if sel is None:
                return InferenceResult(
                    UNAVAILABLE,
                    error="no routable replica for %r (%s)" % (name, reason))
            rep, breaker = sel
            self._begin(rep)
            try:
                faults.fault_point("fleet.replica", replica=rep.rid,
                                   model=name)
                res = rep.server.predict(name, data, timeout_ms=timeout_ms)
            except faults.SimulatedCrash:
                # the ONE place production code catches SimulatedCrash: at
                # the fleet.replica site the crash is the REPLICA's death
                # and this router is the surviving process (faults.py)
                self._replica_died(rep.rid)
                tried.add(rep.rid)
                if attempt < budget:
                    self.stats_sink.on_failover()
                    continue
                return InferenceResult(
                    UNAVAILABLE,
                    error="replica %s died mid-request; failover budget "
                          "exhausted" % rep.rid)
            except faults.InjectedFault as exc:
                # transient/fatal link fault between router and replica:
                # the replica may be fine, but THIS path isn't — count a
                # breaker failure and fail over
                breaker.on_failure()
                tried.add(rep.rid)
                if attempt < budget:
                    self.stats_sink.on_failover()
                    continue
                return InferenceResult(
                    UNAVAILABLE,
                    error="replica %s unreachable (%s); failover budget "
                          "exhausted" % (rep.rid, exc))
            finally:
                self._end(rep)
            if res.status != UNAVAILABLE:
                # the replica answered — reachable from the router's seat.
                # (ERROR/OVERLOADED are the replica's own concern; its
                # per-model breaker and queue bound handle them.)
                breaker.on_success()
                return res
            breaker.on_failure()
            tried.add(rep.rid)
            if attempt < budget:
                self.stats_sink.on_failover()
                continue
            return res
        raise AssertionError("unreachable")   # loop always returns

    def _select(self, name, tried):
        """Pick (replica, breaker) for one attempt, or (None, reason).

        Round-robin over the model's placement, skipping already-tried,
        non-LIVE, and breaker-REJECT replicas.  Unknown model raises."""
        with self._lock:
            if self._closed:
                return None, "fleet stopped"
            if name not in self._specs:
                raise MXNetError("no model %r in the fleet; loaded: %s"
                                 % (name, sorted(self._specs) or "none"))
            placed = list(self._placement.get(name, ()))
            if not placed:
                return None, "no replicas host it"
            cursor = self._rr[name]
            self._rr[name] = cursor + 1
            start = cursor % len(placed)
            order = placed[start:] + placed[:start]
            cands = []
            n_draining = 0
            for rid in order:
                rep = self._replicas[rid]
                if rep.state == DRAINING:
                    n_draining += 1
                if rid in tried or rep.state != LIVE:
                    continue
                cands.append((rep, self._breakers[(name, rid)]))
        if not cands:
            if n_draining:
                return None, "draining"
            return None, "all replicas tried or dead"
        for rep, breaker in cands:
            # admit() outside _lock: the breaker has its own lock, and a
            # REJECT here must not stall other routing threads
            if breaker.admit() != REJECT:
                return (rep, breaker), None
        return None, "all breakers open"

    def _begin(self, rep):
        with self._lock:
            rep.inflight += 1

    def _end(self, rep):
        with self._lock:
            rep.inflight -= 1

    # -- replica death + rebalancing --------------------------------------
    def _replica_died(self, rid, expected=False):
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None or rep.state == DEAD:
                return False
            rep.state = DEAD
            for name, rids in self._placement.items():
                if rid in rids:
                    rids.remove(rid)
                    self._breakers.pop((name, rid), None)
            closed = self._closed
        if not expected:
            self.stats_sink.on_replica_death()
        try:
            rep.server.stop()
        except Exception:
            pass   # it "crashed"; best-effort teardown of the local object
        if not closed:
            # rebalance off the request path: the failing request has
            # already failed over to a warm copy; restoring the replication
            # factor (re-warm included) is background work
            threading.Thread(target=self._rebalance,
                             name="fleet-rebalance", daemon=True).start()
        return True

    def _rebalance(self):
        """Restore every model to min(target, live replicas) copies.

        One (model, replica) deficit at a time: pick the least-loaded live
        candidate under ``_lock``, load + warm OUTSIDE the lock, then
        commit the placement — the re-warm-before-cutover rule."""
        with self._rebalance_mutex:
            failed = set()   # (name, rid) that refused the load this pass
            while True:
                task = None
                with self._lock:
                    if self._closed:
                        return
                    live = [r for r in self._replicas.values()
                            if r.state == LIVE]
                    hosted = {r.rid: 0 for r in live}
                    for rids in self._placement.values():
                        for rid in rids:
                            if rid in hosted:
                                hosted[rid] += 1
                    for name in sorted(self._specs):
                        spec = self._specs[name]
                        placed = self._placement[name]
                        live_placed = [rid for rid in placed
                                       if self._replicas[rid].state == LIVE]
                        want = min(spec.replicas, len(live))
                        if len(live_placed) >= want:
                            continue
                        cands = [r for r in live
                                 if r.rid not in placed
                                 and (name, r.rid) not in failed]
                        if not cands:
                            continue
                        cands.sort(key=lambda r: (hosted[r.rid], r.rid))
                        task = (name, spec, cands[0])
                        break
                    if task is None:
                        return
                name, spec, rep = task
                try:
                    # load + full bucket-menu warmup on the new replica,
                    # BEFORE the placement commit below makes it routable
                    rep.server.load_model(name, spec.block,
                                          spec.input_shapes, **spec.kwargs)
                except MXNetError:
                    failed.add((name, rep.rid))
                    continue
                committed = False
                with self._lock:
                    if (not self._closed and rep.state == LIVE
                            and name in self._specs
                            and rep.rid not in self._placement[name]):
                        self._placement[name].append(rep.rid)
                        self._breakers[(name, rep.rid)] = CircuitBreaker(
                            failure_threshold=self._breaker_threshold,
                            backoff_s=self._breaker_backoff_s,
                            max_backoff_s=self._breaker_max_backoff_s)
                        committed = True
                if committed:
                    self.stats_sink.on_rebalance()
                else:
                    # lost the race (replica died / model unloaded / fleet
                    # stopped while warming): roll the orphan copy back
                    try:
                        rep.server.unload(name)
                    except MXNetError:
                        pass

    def wait_converged(self, timeout_s=10.0):
        """Block until every model has min(target, live) routable copies
        (rebalancing settled).  Returns True on convergence."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                n_live = sum(1 for r in self._replicas.values()
                             if r.state == LIVE)
                done = all(
                    len([rid for rid in self._placement[name]
                         if self._replicas[rid].state == LIVE])
                    >= min(spec.replicas, n_live)
                    for name, spec in self._specs.items())
            if done:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.005)

    # -- observability ----------------------------------------------------
    def health(self, name=None):
        """HEALTHY / DEGRADED / UNAVAILABLE for one model (or the worst
        across the fleet).  A model with zero routable replicas is
        UNAVAILABLE; under target, a non-LIVE placement, or any breaker
        off HEALTHY is DEGRADED."""
        with self._lock:
            if name is not None and name not in self._specs:
                raise MXNetError("no model %r in the fleet; loaded: %s"
                                 % (name, sorted(self._specs) or "none"))
            names = [name] if name is not None else sorted(self._specs)
            n_live = sum(1 for r in self._replicas.values()
                         if r.state == LIVE)
            rows = []
            for n in names:
                placed = list(self._placement[n])
                states = [self._replicas[rid].state for rid in placed]
                breakers = [self._breakers[(n, rid)] for rid in placed
                            if self._replicas[rid].state == LIVE]
                rows.append((n, self._specs[n].replicas, states, breakers))
        worst = HEALTHY
        rank = {HEALTHY: 0, DEGRADED: 1, UNAVAILABLE_HEALTH: 2}
        for _, target, states, breakers in rows:
            n_routable = sum(1 for s in states if s == LIVE)
            if n_routable == 0:
                h = UNAVAILABLE_HEALTH
            else:
                b_health = [b.health() for b in breakers]
                if (any(bh != HEALTHY for bh in b_health)
                        or n_routable < min(target, max(n_live, 1))
                        or any(s != LIVE for s in states)):
                    h = DEGRADED
                else:
                    h = HEALTHY
            if rank[h] > rank[worst]:
                worst = h
        return worst

    def stats(self):
        """Fleet counters + per-replica and per-model routing state."""
        with self._lock:
            reps = {rid: {"state": rep.state, "inflight": rep.inflight,
                          "models": sorted(n for n, rids
                                           in self._placement.items()
                                           if rid in rids)}
                    for rid, rep in self._replicas.items()}
            models = {}
            for name, spec in self._specs.items():
                placed = list(self._placement[name])
                models[name] = {
                    "target": spec.replicas,
                    "placement": placed,
                    "breakers": {rid: self._breakers[(name, rid)]
                                 for rid in placed
                                 if (name, rid) in self._breakers},
                }
        for snap in models.values():
            snap["breakers"] = {rid: b.snapshot()
                                for rid, b in snap["breakers"].items()}
        out = self.stats_sink.snapshot()
        out["replicas"] = reps
        out["models"] = models
        return out

    # -- lifecycle ---------------------------------------------------------
    def stop(self):
        """Stop every replica server; idempotent."""
        with self._lock:
            self._closed = True
            servers = [rep.server for rep in self._replicas.values()
                       if rep.state != DEAD]
        for server in servers:
            try:
                server.stop()
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

def _lookup_replica(replicas, rid):
    """Row lookup over an already-locked replica table."""
    try:
        return replicas[rid]
    except KeyError:
        raise MXNetError("no replica %r; known: %s"
                         % (rid, sorted(replicas) or "none"))
