"""Decode-capable model contract + a self-contained reference LM.

The decode engine does not wrap arbitrary Gluon blocks: an autoregressive
step needs the model to read and write *paged* KV state, which is a
different calling convention from a stateless batch forward.  A decode
model is any object exposing:

* ``vocab_size`` / ``num_layers`` / ``num_heads`` / ``head_dim`` /
  ``max_len`` attributes (the KV pool geometry comes from these);
* ``param_dict()`` -> ``{name: NDArray}`` — live parameter handles, passed
  straight into the engine's CachedOps;
* ``prefill_fn(params, tokens, length, table, k_pool, v_pool)`` — jax
  arrays in, jax arrays out: tokens ``[1, Lb]`` int32 (padded to a prompt
  bucket), length ``[1]`` int32 (the real prompt length), table ``[1, W]``
  int32 page table.  Runs the whole prompt in one causal pass, scatters
  every position's K/V into the sequence's pages, and returns
  ``(logits [1, V] for position length-1, k_pool', v_pool')``;
* ``decode_fn(params, tokens, positions, tables, k_pool, v_pool)`` — one
  token per slot: tokens ``[S]`` int32, positions ``[S]`` int32 (the cache
  index the new token's K/V lands at), tables ``[S, W]`` int32.  Returns
  ``(logits [S, V], k_pool', v_pool')``.

Both functions must be jax-traceable with **shape-only** signatures (no
data-dependent Python control flow): the engine compiles one CachedOp
signature per (prompt bucket) and per (table width bucket) and steady-state
traffic must never add another.

Two optional entry points unlock chunked prefill and speculative decoding
(the engine falls back to ``prefill_fn``/``decode_fn`` when absent):

* ``chunk_prefill_fn(params, tokens, start, length, table, k_pool,
  v_pool)`` — one fixed-size prompt chunk: tokens ``[1, C]`` int32, start
  ``[1]`` int32 (absolute position of the chunk's first token), length
  ``[1]`` int32 (real tokens in this chunk).  Attends to cache positions
  ``0..start+i`` through the page table (earlier chunks' K/V is READ from
  the pool, which is what makes cross-request prefix reuse bitwise-sound),
  scatters this chunk's K/V, and returns logits for row ``length-1``.
* ``verify_fn(params, tokens, positions, valids, tables, k_pool,
  v_pool)`` — the speculative verify step: tokens ``[S, K+1]`` int32 (the
  committed token followed by K draft proposals), positions ``[S]`` int32
  (cache index of the first token), valids ``[S]`` int32 (rows beyond
  ``valids[s]`` write to the trash block and are ignored).  Returns logits
  ``[S, K+1, V]`` — row ``i`` is the model's next-token distribution after
  the first ``i+1`` tokens, so the engine accepts the longest prefix where
  proposal ``i`` equals ``argmax(row i-1)``.

Because a fixed kernel *shape* pins the XLA tiling, all-chunked prefill and
all-verify decode reproduce the sequential reference bitwise only when the
reference itself runs through the SAME chunk/verify signatures (one row
valid at a time).  ``DecodeEngine.generate_reference`` does exactly that.

Exactness contract (the bitwise gate in tests/test_decode.py leans on it):
dead slots and page-table padding use masks whose excluded weights are
EXACTLY zero (``exp(-inf) == 0``), and every per-slot computation is
row-independent — so a slot's logits are bit-identical whether its
neighbors are live, dead, or absent, and whatever table width bucket the
scheduler picked.  ``TinyCausalLM`` is the in-tree reference
implementation: a small pre-norm transformer (learned positions, weight-
tied unembedding) used by the tests, the chaos scenarios, and
``tools/serve_bench.py --profile decode``.
"""
from __future__ import annotations

import numpy as np

__all__ = ["TinyCausalLM"]


def _rms(x):
    import jax.numpy as jnp
    return x / jnp.sqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True)
                        + 1e-6)


class TinyCausalLM:
    """Small causal transformer LM with paged-KV prefill/decode kernels."""

    def __init__(self, vocab_size=48, hidden=32, num_layers=2, num_heads=2,
                 max_len=128, seed=0, eos_id=None, context_attention=None,
                 params=None):
        if hidden % num_heads:
            raise ValueError("hidden must divide into num_heads")
        # name of a bound mesh axis ('sp') to split prompt attention over
        # via the fused ulysses/ring kernels; requires running inside
        # ShardedDecodeModel(sp=n).  None = the bitwise dense path.
        self.context_attention = context_attention
        self.vocab_size = int(vocab_size)
        self.hidden = int(hidden)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = self.hidden // self.num_heads
        self.max_len = int(max_len)
        self.eos_id = eos_id
        from ... import ndarray as nd
        expected = {"embed": (self.vocab_size, self.hidden),
                    "pos": (self.max_len, self.hidden)}
        for l in range(self.num_layers):
            expected["l%d_wq" % l] = (self.hidden, self.hidden)
            expected["l%d_wk" % l] = (self.hidden, self.hidden)
            expected["l%d_wv" % l] = (self.hidden, self.hidden)
            expected["l%d_wo" % l] = (self.hidden, self.hidden)
            expected["l%d_w1" % l] = (self.hidden, 2 * self.hidden)
            expected["l%d_w2" % l] = (2 * self.hidden, self.hidden)
        if params is not None:
            # checkpoint-loaded weights (serving/deploy.py builds each new
            # generation this way) — validate against the geometry before
            # anything can compile a kernel over a half-shaped model
            if set(params) != set(expected):
                missing = sorted(set(expected) - set(params))
                extra = sorted(set(params) - set(expected))
                raise ValueError("params key mismatch: missing %r extra %r"
                                 % (missing, extra))
            loaded = {}
            for k, shape in expected.items():
                arr = params[k]
                if tuple(arr.shape) != shape:
                    raise ValueError("param %r has shape %r, expected %r"
                                     % (k, tuple(arr.shape), shape))
                loaded[k] = arr if isinstance(arr, nd.NDArray) \
                    else nd.array(np.asarray(arr, np.float32))
            self._params = loaded
            return
        rng = np.random.RandomState(seed)
        scale = 1.0 / np.sqrt(self.hidden)

        def w(*shape):
            return nd.array(rng.randn(*shape).astype(np.float32) * scale)

        self._params = {k: w(*shape) for k, shape in expected.items()}

    def param_dict(self):
        return dict(self._params)

    def partition_specs(self):
        """Weight sharding over the serving mesh's 'tp' axis (consumed by
        serving.decode.sharding.ShardedDecodeModel): attention and MLP
        projections split on a hidden-sized axis — always divisible, since
        the head count must divide tp and hidden = heads * head_dim."""
        from jax.sharding import PartitionSpec as P
        specs = {"embed": P(None, "tp"), "pos": P(None, "tp")}
        for l in range(self.num_layers):
            specs["l%d_wq" % l] = P(None, "tp")
            specs["l%d_wk" % l] = P(None, "tp")
            specs["l%d_wv" % l] = P(None, "tp")
            specs["l%d_wo" % l] = P("tp", None)
            specs["l%d_w1" % l] = P(None, "tp")
            specs["l%d_w2" % l] = P("tp", None)
        return specs

    # ------------------------------------------------------------------
    def _qkv(self, p, l, x, n_rows):
        h, d = self.num_heads, self.head_dim
        q = (x @ p["l%d_wq" % l]).reshape(n_rows, h, d)
        k = (x @ p["l%d_wk" % l]).reshape(n_rows, h, d)
        v = (x @ p["l%d_wv" % l]).reshape(n_rows, h, d)
        return q, k, v

    def _mlp(self, p, l, h):
        import jax
        return h + jax.nn.gelu(_rms(h) @ p["l%d_w1" % l]) @ p["l%d_w2" % l]

    def prefill_fn(self, p, tokens, length, table, k_pool, v_pool):
        """Causal pass over one padded prompt; scatters K/V into pages."""
        import jax.numpy as jnp
        bs = k_pool.shape[2]
        L = tokens.shape[1]
        t = tokens[0]
        h = p["embed"][t] + p["pos"][:L]                       # [L, H]
        idx = jnp.arange(L)
        blk = table[0, idx // bs]
        off = idx % bs
        # causal mask: position i attends j <= i; prompt padding sits at
        # j >= length > i for every real row, so it is excluded for free
        causal = idx[None, :] <= idx[:, None]                  # [L, L]
        for l in range(self.num_layers):
            q, k, v = self._qkv(p, l, _rms(h), L)
            # pad-row K/V lands in the trash block / the tail of the
            # sequence's own last block — positions the attention mask
            # never admits before a decode write overwrites them
            k_pool = k_pool.at[l, blk, off].set(k)
            v_pool = v_pool.at[l, blk, off].set(v)
            if self.context_attention is None:
                scores = jnp.einsum("ihd,jhd->hij", q, k) \
                    / jnp.sqrt(float(self.head_dim)).astype(q.dtype)
                scores = jnp.where(causal[None], scores, -jnp.inf)
                w = _softmax(scores)
                att = jnp.einsum("hij,jhd->ihd", w, v).reshape(
                    L, self.hidden)
            else:
                att = self._fused_context_attention(q, k, v, causal)
            h = h + att @ p["l%d_wo" % l]
            h = self._mlp(p, l, h)
        last = _rms(h[length[0] - 1])
        logits = last @ p["embed"].T
        return logits[None], k_pool, v_pool

    def _fused_context_attention(self, q, k, v, causal):
        """Whole-prompt attention through the fused sequence-parallel
        kernels (sharding.long_context_attention): the sequence axis
        splits over the ``context_attention`` mesh axis, Ulysses when the
        head count divides it, streaming ring otherwise.  Allclose — NOT
        bitwise — to the dense path (both kernels mask with -1e30 and the
        ring streams its softmax), and only traceable inside a shard_map
        that binds the axis (ShardedDecodeModel(sp=n)).  Prompt buckets
        the axis extent does not divide run the dense math below."""
        import jax.numpy as jnp
        from .sharding import long_context_attention
        L = q.shape[0]

        def dense(q4, k4, v4):
            s = jnp.einsum("bhid,bhjd->bhij", q4, k4) \
                / jnp.sqrt(float(self.head_dim)).astype(q4.dtype)
            s = jnp.where(causal[None, None], s, -jnp.inf)
            return jnp.einsum("bhij,bhjd->bhid", _softmax(s), v4)

        q4, k4, v4 = (jnp.transpose(x, (1, 0, 2))[None]
                      for x in (q, k, v))
        att4 = long_context_attention(q4, k4, v4, causal=True,
                                      axis_name=self.context_attention,
                                      fallback=dense)
        return jnp.transpose(att4[0], (1, 0, 2)).reshape(L, self.hidden)

    def decode_fn(self, p, tokens, positions, tables, k_pool, v_pool):
        """One fixed-shape decode step for every slot (live or dead)."""
        import jax.numpy as jnp
        bs = k_pool.shape[2]
        S = tokens.shape[0]
        W = tables.shape[1]
        T = W * bs
        srow = jnp.arange(S)
        h = p["embed"][tokens] + p["pos"][positions]           # [S, H]
        blk = tables[srow, positions // bs]
        off = positions % bs
        # valid cache positions: 0..positions[s] inclusive (the new token
        # attends to itself); excluded weights are EXACTLY zero, so table
        # padding and stale pool contents cannot perturb live slots
        mask = jnp.arange(T)[None, :] <= positions[:, None]    # [S, T]
        for l in range(self.num_layers):
            q, k, v = self._qkv(p, l, _rms(h), S)
            k_pool = k_pool.at[l, blk, off].set(k)
            v_pool = v_pool.at[l, blk, off].set(v)
            kseq = k_pool[l][tables].reshape(S, T, self.num_heads,
                                             self.head_dim)
            vseq = v_pool[l][tables].reshape(S, T, self.num_heads,
                                             self.head_dim)
            scores = jnp.einsum("shd,sthd->sht", q, kseq) \
                / jnp.sqrt(float(self.head_dim)).astype(q.dtype)
            scores = jnp.where(mask[:, None, :], scores, -jnp.inf)
            w = _softmax(scores)
            att = jnp.einsum("sht,sthd->shd", w, vseq).reshape(
                S, self.hidden)
            h = h + att @ p["l%d_wo" % l]
            h = self._mlp(p, l, h)
        logits = _rms(h) @ p["embed"].T
        return logits, k_pool, v_pool

    def chunk_prefill_fn(self, p, tokens, start, length, table, k_pool,
                         v_pool):
        """One prompt chunk at absolute positions start..start+C-1.

        Earlier chunks are consumed through the page table (gathered from
        the pool, not recomputed), so a chunk run on top of another
        request's shared prefix pages produces bit-identical K/V and
        logits to a private from-scratch chunked run — the property the
        copy-on-write prefix cache banks on.
        """
        import jax.numpy as jnp
        bs = k_pool.shape[2]
        C = tokens.shape[1]
        W = table.shape[1]
        T = W * bs
        t = tokens[0]
        pos = start[0] + jnp.arange(C)                     # absolute
        h = p["embed"][t] + p["pos"][jnp.clip(pos, 0, self.max_len - 1)]
        blk = table[0, pos // bs]
        off = pos % bs
        valid = jnp.arange(C) < length[0]
        blk = jnp.where(valid, blk, 0)                     # pad -> trash
        # pad rows clamp to position 0 (attend j <= 0): finite garbage,
        # the same dead-slot discipline as decode_fn.  An all-False mask
        # row would softmax to NaN and poison the trash block.
        epos = jnp.where(valid, pos, 0)
        mask = jnp.arange(T)[None, :] <= epos[:, None]     # [C, T]
        for l in range(self.num_layers):
            q, k, v = self._qkv(p, l, _rms(h), C)
            k_pool = k_pool.at[l, blk, off].set(k)
            v_pool = v_pool.at[l, blk, off].set(v)
            kseq = k_pool[l][table[0]].reshape(T, self.num_heads,
                                               self.head_dim)
            vseq = v_pool[l][table[0]].reshape(T, self.num_heads,
                                               self.head_dim)
            scores = jnp.einsum("ihd,jhd->hij", q, kseq) \
                / jnp.sqrt(float(self.head_dim)).astype(q.dtype)
            scores = jnp.where(mask[None], scores, -jnp.inf)
            w = _softmax(scores)
            att = jnp.einsum("hij,jhd->ihd", w, vseq).reshape(
                C, self.hidden)
            h = h + att @ p["l%d_wo" % l]
            h = self._mlp(p, l, h)
        last = _rms(h[length[0] - 1])
        logits = last @ p["embed"].T
        return logits[None], k_pool, v_pool

    def verify_fn(self, p, tokens, positions, valids, tables, k_pool,
                  v_pool):
        """Speculative verify: K+1 tokens per slot in one fixed-shape call.

        Row ``i`` of slot ``s`` is the committed/proposed token at cache
        position ``positions[s] + i``; rows at or past ``valids[s]`` write
        to the trash block and attend position 0 only (finite garbage —
        see chunk_prefill_fn).  Per-row outputs depend only on that row's
        token, its position, and masked pool content, so a verify call
        with one valid row reproduces ``generate_reference`` bitwise and
        extra proposal rows never perturb the accepted prefix.
        """
        import jax.numpy as jnp
        bs = k_pool.shape[2]
        S, K1 = tokens.shape
        W = tables.shape[1]
        T = W * bs
        pos = positions[:, None] + jnp.arange(K1)[None, :]   # [S, K1]
        valid = jnp.arange(K1)[None, :] < valids[:, None]
        h = p["embed"][tokens] \
            + p["pos"][jnp.clip(pos, 0, self.max_len - 1)]   # [S, K1, H]
        blk = jnp.take_along_axis(tables, pos // bs, axis=1)
        blk = jnp.where(valid, blk, 0)                       # -> trash
        off = pos % bs
        epos = jnp.where(valid, pos, 0)
        mask = jnp.arange(T)[None, None, :] <= epos[:, :, None]
        for l in range(self.num_layers):
            x = _rms(h)
            q = (x @ p["l%d_wq" % l]).reshape(S, K1, self.num_heads,
                                              self.head_dim)
            k = (x @ p["l%d_wk" % l]).reshape(S, K1, self.num_heads,
                                              self.head_dim)
            v = (x @ p["l%d_wv" % l]).reshape(S, K1, self.num_heads,
                                              self.head_dim)
            k_pool = k_pool.at[l, blk, off].set(k)
            v_pool = v_pool.at[l, blk, off].set(v)
            kseq = k_pool[l][tables].reshape(S, T, self.num_heads,
                                             self.head_dim)
            vseq = v_pool[l][tables].reshape(S, T, self.num_heads,
                                             self.head_dim)
            scores = jnp.einsum("sihd,sjhd->shij", q, kseq) \
                / jnp.sqrt(float(self.head_dim)).astype(q.dtype)
            scores = jnp.where(mask[:, None, :, :], scores, -jnp.inf)
            w = _softmax(scores)
            att = jnp.einsum("shij,sjhd->sihd", w, vseq).reshape(
                S, K1, self.hidden)
            h = h + att @ p["l%d_wo" % l]
            h = self._mlp(p, l, h)
        logits = _rms(h) @ p["embed"].T                      # [S, K1, V]
        return logits, k_pool, v_pool

    def propose_fn(self, p, tokens, positions, tables, k_pool, v_pool,
                   num_tokens):
        """Greedy draft proposer: ``num_tokens`` unrolled decode steps with
        the argmax on-device, so one compiled call yields K proposals.
        ``num_tokens`` is static (baked into the signature).  Returns
        (proposals ``[S, num_tokens]`` int32, k_pool', v_pool')."""
        import jax.numpy as jnp
        cur = tokens
        pos = positions
        outs = []
        for _ in range(int(num_tokens)):
            logits, k_pool, v_pool = self.decode_fn(
                p, cur, pos, tables, k_pool, v_pool)
            cur = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            outs.append(cur)
            pos = pos + 1
        return jnp.stack(outs, axis=1), k_pool, v_pool


def _softmax(scores):
    """Max-shifted softmax over the last axis with exact-zero masking:
    ``exp(-inf - finite_max) == 0`` exactly, so masked positions contribute
    nothing to the normalizer regardless of the padded width."""
    import jax.numpy as jnp
    m = jnp.max(scores, axis=-1, keepdims=True)
    e = jnp.exp(scores - m)
    return e / jnp.sum(e, axis=-1, keepdims=True)
