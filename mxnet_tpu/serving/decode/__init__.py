"""mxnet_tpu.serving.decode — autoregressive decode engine.

Continuous (iteration-level) batching + a paged KV cache on top of the
CachedOp compile cache: finished sequences leave the fixed-shape decode
step and queued requests join it every iteration, KV memory is a shared
block pool whose usage scales with live tokens, prompts run separately
through a prefill bucket ladder, and tokens stream back per-request with
the serving tier's deadline/backpressure/breaker machinery applied
per-stream.  See docs/SERVING.md#autoregressive-decode.

    from mxnet_tpu.serving.decode import DecodeEngine, TinyCausalLM
    engine = DecodeEngine(TinyCausalLM(), max_slots=8)
    stream = engine.submit([3, 1, 4], max_new_tokens=16, timeout_ms=5000)
    for token in stream:
        ...                       # tokens arrive as they are decoded
    assert stream.status == "OK"
    engine.stop()
"""
from .adapter import GluonCausalLMAdapter, TinyGluonLM
from .engine import DecodeEngine, DecodeStream
from .kv_cache import PagedKVCache
from .model import TinyCausalLM
from .sharding import (ShardedDecodeModel, decode_mesh,
                       expert_sharded_ffn, long_context_attention)
from .stats import DecodeStats

__all__ = ["DecodeEngine", "DecodeStream", "PagedKVCache", "TinyCausalLM",
           "DecodeStats", "ShardedDecodeModel", "decode_mesh",
           "long_context_attention", "expert_sharded_ffn",
           "GluonCausalLMAdapter", "TinyGluonLM"]
