"""Decode-engine observability: per-engine counters + latency windows.

Same two-sink design as ``serving/stats.py``: always-on numeric fields
behind one lock for ``DecodeEngine.stats()``, plus profiler Counters on the
``serving`` Domain — gated on ``profiler.profiling_active()`` — so a trace
shows the decode loop's occupancy next to op spans:

* ``<engine>:live_seqs``      — sequences in decode slots after each step
* ``<engine>:kv_blocks_used`` — allocated KV pool blocks after each step
* ``<engine>:kv_blocks_free`` — absolute pool headroom (the routing signal)
* ``<engine>:ttft_ms``        — time-to-first-token of each prefill
* ``<engine>:tokens_per_s``   — instantaneous decode throughput per step
* ``<engine>:prefix_blocks_shared`` — KV blocks attached via prefix hits
* ``<engine>:spec_accept_rate``     — draft tokens accepted / proposed

Conservation contract (the chaos scenario's invariant): ``requests`` counts
ADMITTED streams and every one of them reaches exactly one terminal
counter, so ``requests == ok + timeouts + errors + unavailable``; ``shed``,
``invalid`` and ``unavailable_rejected`` count fast rejections that never
enter ``requests`` (the same split as ``ModelStats``).
"""
from __future__ import annotations

import threading

from ... import profiler
from ..stats import LatencyWindow

__all__ = ["DecodeStats"]


class DecodeStats:
    """All counters for one decode engine.  Thread-safe."""

    def __init__(self, engine_name, kv_capacity=0, tp_degree=1):
        self._lock = threading.Lock()
        self.requests = 0            # admitted streams
        self.ok = 0
        self.timeouts = 0
        self.errors = 0
        self.unavailable = 0         # admitted, terminated by teardown
        self.shed = 0                # rejected: queue/KV pool full
        self.invalid = 0             # rejected: prompt outside the menu
        self.unavailable_rejected = 0  # rejected: breaker open / closed
        self.retries = 0             # transient execute failures absorbed
        self.prefills = 0
        self.steps = 0               # decode iterations executed
        self.tokens_out = 0          # tokens emitted across all streams
        self.step_slot_sum = 0       # live slots summed over steps
        self.live_seqs = 0
        self.kv_capacity = int(kv_capacity)  # allocatable pool blocks
        self.tp_degree = int(tp_degree)      # mesh devices this engine spans
        self.kv_blocks_used = 0
        self.kv_blocks_free = int(kv_capacity)
        self.tokens_per_s = 0.0      # instantaneous, from the last step
        self.handed_off = 0          # admitted, exported to another engine
        self.imported = 0            # admitted via import_stream
        self.prefix_hits = 0         # admissions that attached shared pages
        self.prefix_blocks_shared = 0  # blocks attached by those hits
        self.cow_forks = 0           # shared pages privatized on write
        self.spec_rounds = 0         # speculative verify dispatches scored
        self.spec_proposed = 0       # draft tokens offered for verification
        self.spec_accepted = 0       # draft tokens the target agreed with
        self._ttft = LatencyWindow()
        self._step_ms = LatencyWindow()
        domain = profiler.Domain("serving")
        self._c_live = domain.new_counter("%s:live_seqs" % engine_name)
        self._c_blocks = domain.new_counter("%s:kv_blocks_used" % engine_name)
        self._c_free = domain.new_counter("%s:kv_blocks_free" % engine_name)
        self._c_ttft = domain.new_counter("%s:ttft_ms" % engine_name)
        self._c_tps = domain.new_counter("%s:tokens_per_s" % engine_name)
        self._c_shared = domain.new_counter(
            "%s:prefix_blocks_shared" % engine_name)
        self._c_accept = domain.new_counter(
            "%s:spec_accept_rate" % engine_name)
        # static for the engine's life: set once so every profiler dump
        # carries the device footprint next to the per-step gauges
        self._c_tp = domain.new_counter("%s:tp_degree" % engine_name)
        self._c_tp.set_value(self.tp_degree)

    # -- event hooks ----------------------------------------------------
    def on_admitted(self):
        with self._lock:
            self.requests += 1

    def on_shed(self):
        with self._lock:
            self.shed += 1

    def on_invalid(self):
        with self._lock:
            self.invalid += 1

    def on_unavailable_rejected(self):
        with self._lock:
            self.unavailable_rejected += 1

    def on_retry(self):
        with self._lock:
            self.retries += 1

    def on_prefill(self, ttft_ms):
        with self._lock:
            self.prefills += 1
            self._ttft.add(ttft_ms)
        if profiler.profiling_active():
            self._c_ttft.set_value(ttft_ms)

    def on_step(self, live, tokens_emitted, step_ms, kv_blocks_used):
        with self._lock:
            self.steps += 1
            self.step_slot_sum += live
            self.tokens_out += tokens_emitted
            self.live_seqs = live
            self.kv_blocks_used = kv_blocks_used
            self.kv_blocks_free = max(0, self.kv_capacity - kv_blocks_used)
            free = self.kv_blocks_free
            if step_ms > 0:
                self.tokens_per_s = tokens_emitted / (step_ms / 1e3)
            self._step_ms.add(step_ms)
        if profiler.profiling_active():
            self._c_live.set_value(live)
            self._c_blocks.set_value(kv_blocks_used)
            self._c_free.set_value(free)
            if step_ms > 0:
                self._c_tps.set_value(tokens_emitted / (step_ms / 1e3))

    def on_tokens(self, n):
        """Tokens emitted outside a decode step (the prefill's first)."""
        with self._lock:
            self.tokens_out += n

    def on_idle(self, live, kv_blocks_used):
        """Occupancy update without a step (join/finish bookkeeping)."""
        with self._lock:
            self.live_seqs = live
            self.kv_blocks_used = kv_blocks_used
            self.kv_blocks_free = max(0, self.kv_capacity - kv_blocks_used)
            free = self.kv_blocks_free
        if profiler.profiling_active():
            self._c_live.set_value(live)
            self._c_blocks.set_value(kv_blocks_used)
            self._c_free.set_value(free)

    def on_prefix(self, blocks_shared):
        """A fresh admission resolved its prompt against the prefix
        registry: ``blocks_shared`` pages attached without prefill work
        (0 means the lookup missed — only hits count)."""
        if blocks_shared <= 0:
            return
        with self._lock:
            self.prefix_hits += 1
            self.prefix_blocks_shared += blocks_shared
            shared = self.prefix_blocks_shared
        if profiler.profiling_active():
            self._c_shared.set_value(shared)

    def on_cow_fork(self):
        """A shared page was privatized on first divergent write."""
        with self._lock:
            self.cow_forks += 1

    def on_spec(self, proposed, accepted):
        """One speculative round settled for one greedy slot: ``proposed``
        draft tokens were verified, ``accepted`` agreed with the target."""
        with self._lock:
            self.spec_rounds += 1
            self.spec_proposed += proposed
            self.spec_accepted += accepted
            rate = (self.spec_accepted / self.spec_proposed
                    if self.spec_proposed else 0.0)
        if profiler.profiling_active():
            self._c_accept.set_value(rate)

    def on_handed_off(self):
        """An admitted stream left this engine via ``export_stream`` — it
        terminates elsewhere, so it leaves this engine's conservation set
        through ``handed_off`` instead of a terminal counter."""
        with self._lock:
            self.handed_off += 1

    def on_imported(self):
        """A stream entered via ``import_stream`` — joins the conservation
        set on the ``imported`` side: ``requests + imported ==
        ok + timeouts + errors + unavailable + handed_off``."""
        with self._lock:
            self.imported += 1

    def on_result(self, status):
        from ..server import OK, TIMEOUT, ERROR, UNAVAILABLE
        with self._lock:
            if status == OK:
                self.ok += 1
            elif status == TIMEOUT:
                self.timeouts += 1
            elif status == ERROR:
                self.errors += 1
            elif status == UNAVAILABLE:
                self.unavailable += 1

    # -- snapshot -------------------------------------------------------
    def snapshot(self):
        with self._lock:
            return {
                "requests": self.requests,
                "ok": self.ok,
                "timeouts": self.timeouts,
                "errors": self.errors,
                "unavailable": self.unavailable,
                "shed": self.shed,
                "invalid": self.invalid,
                "unavailable_rejected": self.unavailable_rejected,
                "retries": self.retries,
                "prefills": self.prefills,
                "steps": self.steps,
                "tokens_out": self.tokens_out,
                "avg_live_slots": (self.step_slot_sum / self.steps
                                   if self.steps else 0.0),
                "live_seqs": self.live_seqs,
                "kv_capacity": self.kv_capacity,
                "tp_degree": self.tp_degree,
                "kv_blocks_used": self.kv_blocks_used,
                "kv_blocks_free": self.kv_blocks_free,
                "tokens_per_s": self.tokens_per_s,
                "handed_off": self.handed_off,
                "imported": self.imported,
                "prefix_hits": self.prefix_hits,
                "prefix_blocks_shared": self.prefix_blocks_shared,
                "cow_forks": self.cow_forks,
                "spec_rounds": self.spec_rounds,
                "spec_proposed": self.spec_proposed,
                "spec_accepted": self.spec_accepted,
                "spec_accept_rate": (self.spec_accepted / self.spec_proposed
                                     if self.spec_proposed else 0.0),
                "ttft_ms": self._ttft.percentiles(ps=(50, 95, 99)),
                "step_ms": self._step_ms.percentiles(ps=(50, 95, 99)),
            }
