"""Tensor/sequence-sharded decode: any decode-model contract over a mesh.

A model whose K/V pool or weights exceed one device serves through
:class:`ShardedDecodeModel`, a wrapper that satisfies the SAME contract
as the model it wraps (model.py docstring) but stores its state sharded
over a ``tp`` mesh axis:

* **paged K/V pools are head-sharded device arrays** — the pool keeps the
  contract layout ``[layers, blocks, block_size, heads, dim]`` but the
  heads axis is split ``heads/tp`` per device (page tables and the
  block-0 trash-block convention are replicated, so the PagedKVCache
  host-side accounting is untouched);
* **weights are sharded per the model's ``partition_specs()``** — one
  PartitionSpec per parameter (attention projections by head, MLP by the
  wide axis), unresolvable or absent specs replicate;
* **every contract fn runs as a ``shard_map``** over the mesh: each
  device all-gathers the shards it needs *at use*, runs the inner
  model's kernel on the full operand, and slices the K/V carry back to
  its local head shard.  The gathered compute is replicated — arithmetic
  identical to the single-device run — which is what makes sharded
  decode BITWISE-equal to the unsharded reference (the PR 10 lesson:
  GSPMD-propagated partitioning re-tiles reductions and breaks bitwise;
  gather-at-use moves data, never changes the math).  The persistent
  footprint is 1/tp per device; the transient gather is the price, and
  the fused ``sp`` path below is the escape hatch when it matters.

Long-context attention routes through the dormant ``parallel/`` kernels:
:func:`long_context_attention` is an inside-``shard_map`` router that
splits the sequence over an ``sp`` axis and dispatches Ulysses all-to-all
head sharding (`ulysses.py`) when heads divide the axis, streaming ring
attention (`ring_attention.py`) otherwise, then gathers the full output
back.  MoE feed-forward layers shard experts the same way through
:func:`expert_sharded_ffn` (`moe.py`).  Both are the *fused* production
paths: numerically allclose to the dense reference (they mask with -1e30
and stream the softmax), so a model opts in per layer — the default
gather-at-use path keeps the bitwise gate.

Sharding-shape validation happens HERE, eagerly, with ValueErrors naming
both extents (the `shard_batch` convention) — never as a shape error
inside ``shard_map``.
"""
from __future__ import annotations

import numpy as _np

__all__ = ["ShardedDecodeModel", "decode_mesh", "long_context_attention",
           "expert_sharded_ffn", "check_tp_divisible",
           "check_pool_matches_mesh", "POOL_HEAD_AXIS"]

# contract pool layout [layers, blocks, block_size, heads, dim]: the axis
# the 'tp' shards split
POOL_HEAD_AXIS = 3


def check_tp_divisible(name, extent, tp, what="head count", axis="tp"):
    """Raise ValueError naming both extents unless ``extent % tp == 0``."""
    if int(extent) % int(tp):
        raise ValueError(
            "%s: %s of %d is not divisible by the mesh %r axis extent %d"
            % (name, what, int(extent), axis, int(tp)))
    return int(extent) // int(tp)


def check_pool_matches_mesh(name, pool_shape, mesh):
    """A K/V pool is head-shardable over ``mesh`` iff its head axis
    divides the 'tp' extent; raise naming both extents otherwise."""
    tp = int(mesh.shape["tp"])
    if len(pool_shape) != 5:
        raise ValueError(
            "%s: pool shape %r is not the contract layout "
            "[layers, blocks, block_size, heads, dim]"
            % (name, tuple(pool_shape)))
    check_tp_divisible(name, pool_shape[POOL_HEAD_AXIS], tp,
                       what="pool head axis")
    return tp


def decode_mesh(tp, sp=1, devices=None):
    """Build the ('tp', 'sp') serving mesh over EXACTLY tp*sp devices.

    ``make_mesh`` folds leftover devices into the leading axis — right
    for training (use everything), wrong for serving where a tp=2 engine
    must consume exactly 2 devices so the fleet can place others on the
    rest.  Raises ValueError naming both extents when the machine cannot
    honor the request."""
    import jax
    from jax.sharding import Mesh
    tp, sp = int(tp), int(sp)
    if tp < 1 or sp < 1:
        raise ValueError("decode_mesh: tp=%d, sp=%d must both be >= 1"
                         % (tp, sp))
    if devices is None:
        devices = jax.devices()
    need = tp * sp
    if len(devices) < need:
        raise ValueError(
            "decode_mesh: tp=%d x sp=%d needs %d device(s); only %d "
            "available" % (tp, sp, need, len(devices)))
    dev = _np.array(devices[:need]).reshape(tp, sp)
    return Mesh(dev, ("tp", "sp"))


# ---------------------------------------------------------------------------
# fused long-context / MoE paths (inside-shard_map helpers)
# ---------------------------------------------------------------------------

def long_context_attention(q, k, v, causal=True, axis_name="sp",
                           fallback=None):
    """Sequence-parallel attention for use INSIDE a shard_map body.

    Takes the FULL ``[B, H, T, D]`` operands (replicated across the
    ``sp`` members, as the gather-at-use serving path leaves them),
    splits the sequence so each member computes its T/n slice through
    the Ulysses all-to-all kernel when ``H % n == 0`` — one head group
    per member, full sequence per head — or the streaming ring kernel
    otherwise, then all-gathers the slices back to the full output every
    member returns.  Numerically allclose (NOT bitwise) to dense masked
    attention: both kernels mask with -1e30 and the ring streams its
    softmax.  T must divide the axis extent; when it does not (short
    prompt buckets) the call routes to ``fallback(q, k, v)`` if given —
    the model's own dense attention — and raises the ValueError naming
    both extents otherwise."""
    import jax
    from ...parallel import allgather, axis_size, ring_attention, \
        ulysses_attention_local
    n = axis_size(axis_name)
    T = q.shape[2]
    if fallback is not None and (n == 1 or T % n):
        return fallback(q, k, v)
    loc = check_tp_divisible("long_context_attention", T, n,
                             what="sequence length", axis=axis_name)
    i = jax.lax.axis_index(axis_name)
    ql, kl, vl = (jax.lax.dynamic_slice_in_dim(x, i * loc, loc, axis=2)
                  for x in (q, k, v))
    if q.shape[1] % n == 0:
        out = ulysses_attention_local(ql, kl, vl, axis_name=axis_name,
                                      causal=causal)
    else:
        out = ring_attention(ql, kl, vl, axis_name=axis_name,
                             causal=causal)
    return allgather(out, axis_name, axis=2, tiled=True)  # mxshard: gather-ok(restore the full T axis every sp member returns; allclose fused path, not bitwise)


def expert_sharded_ffn(expert_fn, expert_params, gate_w, x, axis_name="sp",
                       k=2, capacity_factor=2.0):
    """Expert-parallel MoE feed-forward for use INSIDE a shard_map body.

    ``x`` is a ``[tokens, hidden]`` batch replicated across the axis
    members; experts dispatch through ``moe_apply`` (GShard dense
    dispatch, Switch overflow) with the expert set spread over the axis.
    The token count must divide the axis extent (moe_apply shards the
    token batch; ValueError names both extents here, not inside the
    collective)."""
    from ...parallel import axis_size
    from ...parallel.moe import moe_apply
    n = axis_size(axis_name)
    check_tp_divisible("expert_sharded_ffn", x.shape[0], n,
                       what="token count", axis=axis_name)
    check_tp_divisible("expert_sharded_ffn", gate_w.shape[-1], n,
                       what="expert count", axis=axis_name)
    return moe_apply(expert_fn, expert_params, gate_w, x,
                     axis_name=axis_name, k=k,
                     capacity_factor=capacity_factor)


# ---------------------------------------------------------------------------
# the sharded contract wrapper
# ---------------------------------------------------------------------------

class ShardedDecodeModel:
    """Run a decode-model contract storage-sharded over a ('tp','sp') mesh.

    Satisfies the full contract of the wrapped model (same attrs, same
    fn signatures, ``chunk_prefill_fn``/``verify_fn``/``propose_fn``
    present iff the inner model has them), so DecodeEngine, the prefix
    cache, speculative decode, export/import handoff and the sequential
    reference all compose unchanged.  Three extra hooks the engine picks
    up when present:

    * ``zeros_pool(shape)`` — fresh head-sharded K/V pool storage;
    * ``place_inputs(x)`` — pins per-step host inputs replicated on the
      mesh (a jit call cannot mix single-device-committed and
      mesh-committed operands);
    * ``tp_degree`` / ``sp_degree`` — the fleet's device-footprint
      accounting (`FleetRouter.load_decode(..., tp=k)`).

    Exported pages (`export_stream`) host-gather to the full head axis,
    so sharded→sharded and sharded→unsharded handoffs are bitwise
    round trips with no geometry change.
    """

    def __init__(self, model, tp=2, sp=1, devices=None):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ...ndarray import NDArray
        self._inner = model
        self.tp = int(tp)
        self.sp = int(sp)
        self.tp_degree = self.tp
        self.sp_degree = self.sp
        # contract geometry proxies (export/import geometry dicts and the
        # PagedKVCache pool grid come from these)
        self.vocab_size = model.vocab_size
        self.num_layers = model.num_layers
        self.num_heads = model.num_heads
        self.head_dim = model.head_dim
        self.max_len = model.max_len
        self.eos_id = getattr(model, "eos_id", None)
        self._local_heads = check_tp_divisible(
            type(model).__name__, model.num_heads, self.tp)
        self.mesh = decode_mesh(self.tp, self.sp, devices)
        if int(self.mesh.shape["tp"]) != self.tp:
            raise ValueError(
                "ShardedDecodeModel: mesh 'tp' extent %d does not match "
                "the requested tp degree %d"
                % (int(self.mesh.shape["tp"]), self.tp))
        # no trailing None: shard_map normalizes its out_specs that way,
        # and jit's executable cache keys on sharding EQUALITY — a fresh
        # zeros_pool must carry the byte-same sharding as a pool carried
        # out of a step, or the first post-warmup step stealth-recompiles
        self._pool_sharding = NamedSharding(
            self.mesh, P(None, None, None, "tp"))
        self._replicated = NamedSharding(self.mesh, P())

        # resolve one PartitionSpec per parameter and place the weights
        raw = {}
        if hasattr(model, "partition_specs"):
            raw = dict(model.partition_specs())
        inner_params = model.param_dict()
        self._pspecs = {}
        self._params = {}
        for name in sorted(inner_params):
            spec = self._check_spec(name, raw.get(name),
                                    inner_params[name].shape)
            self._pspecs[name] = spec
            self._params[name] = NDArray(jax.device_put(
                inner_params[name]._data, NamedSharding(self.mesh, spec)))

        self._prefill_sm = self._build("prefill_fn", 3)
        self._decode_sm = self._build("decode_fn", 3)
        if hasattr(model, "chunk_prefill_fn"):
            self._chunk_sm = self._build("chunk_prefill_fn", 4)
            self.chunk_prefill_fn = self._make_call(self._chunk_sm, 4)
        if hasattr(model, "verify_fn"):
            self._verify_sm = self._build("verify_fn", 4)
            self.verify_fn = self._make_call(self._verify_sm, 4)
        if hasattr(model, "propose_fn"):
            self._propose_sms = {}
            self.propose_fn = self._propose_call

    # -- contract surface ------------------------------------------------
    def param_dict(self):
        """Live mesh-sharded parameter handles (same-name contract)."""
        return dict(self._params)

    def prefill_fn(self, p, tokens, length, table, k_pool, v_pool):
        return self._prefill_sm(p, (tokens, length, table), k_pool, v_pool)

    def decode_fn(self, p, tokens, positions, tables, k_pool, v_pool):
        return self._decode_sm(p, (tokens, positions, tables), k_pool,
                               v_pool)

    def _propose_call(self, p, tokens, positions, tables, k_pool, v_pool,
                      num_tokens):
        sm = self._propose_sms.get(int(num_tokens))
        if sm is None:
            inner = self._inner

            def fn(pf, toks, pos, tabs, kf, vf, _n=int(num_tokens)):
                return inner.propose_fn(pf, toks, pos, tabs, kf, vf, _n)

            sm = self._build_fn(fn, 3)
            self._propose_sms[int(num_tokens)] = sm
        return sm(p, (tokens, positions, tables), k_pool, v_pool)

    # -- engine hooks ----------------------------------------------------
    def zeros_pool(self, shape):
        """Fresh zeroed head-sharded pool storage for ``shape`` (the
        contract layout; the head axis must divide tp)."""
        import jax
        import jax.numpy as jnp
        from ...ndarray import NDArray
        check_pool_matches_mesh(type(self._inner).__name__, shape,
                                self.mesh)
        return NDArray(jax.device_put(jnp.zeros(shape, jnp.float32),
                                      self._pool_sharding))

    def place_inputs(self, x):
        """Pin a per-step operand on the serving mesh (replicated) unless
        it already lives there; mesh-resident pools/params pass through
        untouched so their shardings stay byte-stable across steps."""
        import jax
        from jax.sharding import NamedSharding
        sh = getattr(x, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.mesh == self.mesh:
            return x
        return jax.device_put(x, self._replicated)

    # -- internals -------------------------------------------------------
    def _check_spec(self, name, spec, shape):
        """Validate a parameter PartitionSpec eagerly: only the 'tp' axis,
        one axis name per dim, and the dim must divide the extent."""
        from jax.sharding import PartitionSpec as P
        if spec is None:
            return P()
        entries = tuple(spec)
        if len(entries) > len(shape):
            raise ValueError(
                "%s: partition spec %r has %d entries for a rank-%d "
                "parameter" % (name, spec, len(entries), len(shape)))
        for dim, ax in enumerate(entries):
            if ax is None:
                continue
            if ax != "tp":
                raise ValueError(
                    "%s: partition spec %r names axis %r; decode weight "
                    "sharding supports only the 'tp' mesh axis"
                    % (name, spec, ax))
            check_tp_divisible(name, shape[dim], self.tp,
                               what="dim %d extent" % dim)
        return P(*entries)

    def _build(self, fn_name, n_small):
        inner_fn = getattr(self._inner, fn_name)
        return self._build_fn(inner_fn, n_small)

    def _build_fn(self, inner_fn, n_small):
        """shard_map the contract fn: gather shards at use, run the inner
        kernel on full operands (replicated math => bitwise), slice the
        K/V carries back to the local head shard."""
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        from ...parallel import allgather
        pool_spec = P(None, None, None, "tp")
        pspecs = dict(self._pspecs)
        lh = self._local_heads

        def gathered(v, spec):
            for dim, ax in enumerate(tuple(spec)):
                if ax is not None:
                    v = allgather(v, ax, axis=dim, tiled=True)  # mxshard: gather-ok(gather-at-use weight tax: replicated math keeps decode bitwise; ROADMAP item 1 deletes this tag)
            return v

        # the gather-at-use region does NO reductions — replicated math is
        # the bitwise contract.  Item 1's compute-parallel kernels will
        # raise this to the Megatron one-psum-per-block budget.
        # The decode step's declared worst case: every gather-at-use temp
        # (full params once per sharded dim + both full K/V pools) live at
        # once under the accountant's reuse-free model —
        # predict_decode_step_peak_bytes() is the exact symbolic form,
        # pinned == the runtime peak in BENCH_SHARDED_DECODE.json.
        # mxmem: budget(hbm=64MB)
        # mxshard: budget(psum=0)
        def body(p_local, small, k_local, v_local):
            p_full = {n: gathered(v, pspecs[n])
                      for n, v in p_local.items()}
            k_full = allgather(k_local, "tp", axis=POOL_HEAD_AXIS,  # mxshard: gather-ok(gather-at-use K-pool tax: full head axis for the inner kernel; ROADMAP item 1 deletes this tag)
                               tiled=True)
            v_full = allgather(v_local, "tp", axis=POOL_HEAD_AXIS,  # mxshard: gather-ok(gather-at-use V-pool tax: full head axis for the inner kernel; ROADMAP item 1 deletes this tag)
                               tiled=True)
            out, kp, vp = inner_fn(p_full, *small, k_full, v_full)
            i = jax.lax.axis_index("tp")
            kp = jax.lax.dynamic_slice_in_dim(kp, i * lh, lh,
                                              axis=POOL_HEAD_AXIS)
            vp = jax.lax.dynamic_slice_in_dim(vp, i * lh, lh,
                                              axis=POOL_HEAD_AXIS)
            return out, kp, vp

        return shard_map(
            body, mesh=self.mesh,
            in_specs=(pspecs, tuple(P() for _ in range(n_small)),
                      pool_spec, pool_spec),
            out_specs=(P(), pool_spec, pool_spec),
            check_rep=False)

    @staticmethod
    def _make_call(sm, n_small):
        def call(p, *args):
            return sm(p, tuple(args[:n_small]), args[n_small],
                      args[n_small + 1])
        return call
