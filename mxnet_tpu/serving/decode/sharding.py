"""Tensor-sharded decode: compute-parallel Megatron kernels over a mesh.

A model whose K/V pool or weights exceed one device serves through
:class:`ShardedDecodeModel`, a wrapper that satisfies the SAME contract
as the model it wraps (model.py docstring) but keeps both storage AND
compute on the shard over a ``tp`` mesh axis:

* **paged K/V pools are head-sharded device arrays** — the pool keeps the
  contract layout ``[layers, blocks, block_size, heads, dim]`` but the
  heads axis is split ``heads/tp`` per device (page tables and the
  block-0 trash-block convention are replicated, so the PagedKVCache
  host-side accounting is untouched);
* **weights are sharded per the model's ``partition_specs()``** — the
  Megatron recipe those specs already encode: qkv/up projections
  column-parallel (``P(None, 'tp')``), wo/down row-parallel
  (``P('tp', None)``), embedding/positions column-sharded;
* **every contract fn runs as a ``shard_map``** of a compute-parallel
  kernel: each device contracts its LOCAL weight shard against the
  replicated residual stream, runs paged attention over its LOCAL head
  slice of the pool (the new K/V never leave their shard — no gather at
  all), and each Megatron half-block ends in exactly ONE psum of the
  row-parallel partial products.  A decode step's whole collective bill
  is ``2 * num_layers + 2`` psums (one exact scatter-assembly psum for
  the column-sharded embedding, two block psums per layer, one for the
  weight-tied unembedding) and ZERO all_gathers — the PR 15
  gather-at-use wrapper paid 16 gathers per step for bitwise math; this
  kernel deletes that tax.

**Exactness policy** (the documented bitwise relaxation): psum member
order differs from the single-device serial reduction, so sharded logits
are ALLCLOSE — not bitwise — to the unsharded reference.  Greedy token
streams stay token-identical (the engine gate), sampled streams replay
token-identically through the host-side float64 sampler, and any two
runs of the SAME sharded geometry remain bitwise because XLA's reduction
order is deterministic per executable.  The two psums whose inputs have
exactly one nonzero contributor per element (embedding assembly) stay
order-free and bitwise-exact by construction.

**Quantized wire** (opt-in): ``ShardedDecodeModel(..., wire="2bit",
wire_threshold=t)`` routes the per-block psums through the PR 10
error-feedback sign codec (``gradient_compression.quantize_2bit``) in
its stateless serving instantiation — ±1 int8 codes at ``|y| >= t``,
psum of the codes on the wire (4x fewer bytes than fp32), dequantized
``* t`` on arrival.  Fixed-shape decode steps cannot carry a residual,
so the codec runs residual-free and is LOSSY: an accuracy envelope, not
an exactness gate.  The embedding-assembly and unembedding psums stay
exact fp32 so the argmax surface is never quantized.

Long-context attention routes through the dormant ``parallel/`` kernels:
:func:`long_context_attention` is an inside-``shard_map`` router that
splits the sequence over an ``sp`` axis and dispatches Ulysses all-to-all
head sharding (`ulysses.py`) when heads divide the axis, streaming ring
attention (`ring_attention.py`) otherwise, then gathers the full output
back.  MoE feed-forward layers shard experts the same way through
:func:`expert_sharded_ffn` (`moe.py`).  Both are *fused* paths outside
the decode-step psum budget; a model that sets ``context_attention``
cannot wrap in :class:`ShardedDecodeModel` (the compute-parallel kernels
run head-local attention and do not route the fused path).

Sharding-shape validation happens HERE, eagerly, with ValueErrors naming
both extents (the `shard_batch` convention) — never as a shape error
inside ``shard_map``.
"""
from __future__ import annotations

import numpy as _np

from .model import _rms, _softmax

__all__ = ["ShardedDecodeModel", "decode_mesh", "long_context_attention",
           "expert_sharded_ffn", "check_tp_divisible",
           "check_pool_matches_mesh", "POOL_HEAD_AXIS"]

# contract pool layout [layers, blocks, block_size, heads, dim]: the axis
# the 'tp' shards split
POOL_HEAD_AXIS = 3

# the canonical decode-model parameter schema the compute-parallel
# kernels are written against (TinyCausalLM and the Gluon adapter both
# emit it): per-layer dense roles plus "embed"/"pos"
_DENSE_ROLES = ("wq", "wk", "wv", "wo", "w1", "w2")


def check_tp_divisible(name, extent, tp, what="head count", axis="tp"):
    """Raise ValueError naming both extents unless ``extent % tp == 0``."""
    if int(extent) % int(tp):
        raise ValueError(
            "%s: %s of %d is not divisible by the mesh %r axis extent %d"
            % (name, what, int(extent), axis, int(tp)))
    return int(extent) // int(tp)


def check_pool_matches_mesh(name, pool_shape, mesh):
    """A K/V pool is head-shardable over ``mesh`` iff its head axis
    divides the 'tp' extent; raise naming both extents otherwise."""
    tp = int(mesh.shape["tp"])
    if len(pool_shape) != 5:
        raise ValueError(
            "%s: pool shape %r is not the contract layout "
            "[layers, blocks, block_size, heads, dim]"
            % (name, tuple(pool_shape)))
    check_tp_divisible(name, pool_shape[POOL_HEAD_AXIS], tp,
                       what="pool head axis")
    return tp


def decode_mesh(tp, sp=1, devices=None):
    """Build the ('tp', 'sp') serving mesh over EXACTLY tp*sp devices.

    ``make_mesh`` folds leftover devices into the leading axis — right
    for training (use everything), wrong for serving where a tp=2 engine
    must consume exactly 2 devices so the fleet can place others on the
    rest.  Raises ValueError naming both extents when the machine cannot
    honor the request."""
    import jax
    from jax.sharding import Mesh
    tp, sp = int(tp), int(sp)
    if tp < 1 or sp < 1:
        raise ValueError("decode_mesh: tp=%d, sp=%d must both be >= 1"
                         % (tp, sp))
    if devices is None:
        devices = jax.devices()
    need = tp * sp
    if len(devices) < need:
        raise ValueError(
            "decode_mesh: tp=%d x sp=%d needs %d device(s); only %d "
            "available" % (tp, sp, need, len(devices)))
    dev = _np.array(devices[:need]).reshape(tp, sp)
    return Mesh(dev, ("tp", "sp"))


# ---------------------------------------------------------------------------
# fused long-context / MoE paths (inside-shard_map helpers)
# ---------------------------------------------------------------------------

def long_context_attention(q, k, v, causal=True, axis_name="sp",
                           fallback=None):
    """Sequence-parallel attention for use INSIDE a shard_map body.

    Takes the FULL ``[B, H, T, D]`` operands (replicated across the
    ``sp`` members), splits the sequence so each member computes its T/n
    slice through the Ulysses all-to-all kernel when ``H % n == 0`` — one
    head group per member, full sequence per head — or the streaming ring
    kernel otherwise, then all-gathers the slices back to the full output
    every member returns.  Numerically allclose (NOT bitwise) to dense
    masked attention: both kernels mask with -1e30 and the ring streams
    its softmax.  T must divide the axis extent; when it does not (short
    prompt buckets) the call routes to ``fallback(q, k, v)`` if given —
    the model's own dense attention — and raises the ValueError naming
    both extents otherwise."""
    import jax
    from ...parallel import allgather, axis_size, ring_attention, \
        ulysses_attention_local
    n = axis_size(axis_name)
    T = q.shape[2]
    if fallback is not None and (n == 1 or T % n):
        return fallback(q, k, v)
    loc = check_tp_divisible("long_context_attention", T, n,
                             what="sequence length", axis=axis_name)
    i = jax.lax.axis_index(axis_name)
    ql, kl, vl = (jax.lax.dynamic_slice_in_dim(x, i * loc, loc, axis=2)
                  for x in (q, k, v))
    if q.shape[1] % n == 0:
        out = ulysses_attention_local(ql, kl, vl, axis_name=axis_name,
                                      causal=causal)
    else:
        out = ring_attention(ql, kl, vl, axis_name=axis_name,
                             causal=causal)
    return allgather(out, axis_name, axis=2, tiled=True)  # mxshard: gather-ok(restore the full T axis every sp member returns; allclose fused path, not bitwise)


def expert_sharded_ffn(expert_fn, expert_params, gate_w, x, axis_name="sp",
                       k=2, capacity_factor=2.0):
    """Expert-parallel MoE feed-forward for use INSIDE a shard_map body.

    ``x`` is a ``[tokens, hidden]`` batch replicated across the axis
    members; experts dispatch through ``moe_apply`` (GShard dense
    dispatch, Switch overflow) with the expert set spread over the axis.
    The token count must divide the axis extent (moe_apply shards the
    token batch; ValueError names both extents here, not inside the
    collective)."""
    from ...parallel import axis_size
    from ...parallel.moe import moe_apply
    n = axis_size(axis_name)
    check_tp_divisible("expert_sharded_ffn", x.shape[0], n,
                       what="token count", axis=axis_name)
    check_tp_divisible("expert_sharded_ffn", gate_w.shape[-1], n,
                       what="expert count", axis=axis_name)
    return moe_apply(expert_fn, expert_params, gate_w, x,
                     axis_name=axis_name, k=k,
                     capacity_factor=capacity_factor)


# ---------------------------------------------------------------------------
# compute-parallel kernels (inside shard_map; every operand is the LOCAL
# shard, the residual stream h is replicated)
# ---------------------------------------------------------------------------

class _Geometry:
    """Static per-model facts the compute-parallel kernels close over."""

    __slots__ = ("num_layers", "num_heads", "local_heads", "head_dim",
                 "hidden", "hidden_local", "vocab_size", "max_len", "tp",
                 "gluon", "wire", "wire_threshold")

    def __init__(self, **kw):
        for name in self.__slots__:
            setattr(self, name, kw[name])


def _contract_local(geom, p):
    """Normalize local weight shards to the contract layout.

    Gluon dense layers store ``[units, in]`` — the transpose of the
    contract's ``[in, units]``.  Transposition swaps the sharded dim too,
    so the transpose of a Gluon LOCAL shard is exactly the contract
    layout's local shard: layout is erased device-locally, zero
    collectives."""
    if not geom.gluon:
        return p
    out = dict(p)
    for l in range(geom.num_layers):
        for role in _DENSE_ROLES:
            key = "l%d_%s" % (l, role)
            out[key] = out[key].T
    return out


def _assemble_replicated(geom, part):
    """Exact replicated assembly of a column-sharded activation.

    ``part`` is this member's ``hidden/tp`` column slice (embedding +
    positions read from the column-sharded tables).  Scatter it into a
    zeros-backed full-width buffer at the member's offset and psum: every
    element has exactly ONE nonzero contributor, so the reduction is
    order-free and bitwise-exact.  Deliberately a psum rather than an
    all_gather — it keeps the decode region inside the psum-only budget
    and XLA lowers a one-hot all-reduce to the same ICI traffic."""
    import jax
    import jax.numpy as jnp
    from ...parallel import allreduce
    i = jax.lax.axis_index("tp")
    full = jnp.zeros(part.shape[:-1] + (geom.hidden,), part.dtype)
    full = jax.lax.dynamic_update_slice_in_dim(
        full, part, i * geom.hidden_local, axis=part.ndim - 1)
    return allreduce(full, "tp")  # mxshard: allclose-ok(scatter-assembly psum: one nonzero contributor per element, order-free and bitwise-exact by construction)


def _block_psum(geom, y):
    """The ONE collective of a Megatron half-block: sum the row-parallel
    partial products (attention output after wo, MLP output after w2).
    Psum member order differs from the single-device serial sum, so the
    result is allclose — greedy token streams stay token-identical (the
    engine gate).  ``wire="2bit"`` reroutes through the sign codec."""
    from ...parallel import allreduce
    if geom.wire == "2bit":
        return _psum_2bit(geom, y)
    return allreduce(y, "tp")  # mxshard: allclose-ok(Megatron row-parallel reduction: psum member order differs from the single-device serial sum; logits allclose, greedy tokens identical)


def _psum_2bit(geom, y):
    """Quantized block psum: the PR 10 2-bit error-feedback codec
    (``gradient_compression.quantize_2bit``) in its stateless serving
    instantiation.  Fixed-shape decode steps cannot carry a residual
    across calls, so the codec runs residual-free: ±1 int8 codes where
    ``|y| >= wire_threshold``, int8 codes summed on the wire (4x fewer
    bytes than the fp32 partials), dequantized ``* wire_threshold`` on
    arrival.  Lossy by design — the accuracy envelope is documented in
    docs/SERVING.md and gated by tests, not by the bitwise contract."""
    import jax.numpy as jnp
    from ...gradient_compression import quantize_2bit
    from ...parallel import allreduce
    thr = geom.wire_threshold
    codes, _ = quantize_2bit(y, jnp.zeros_like(y), thr)
    total = allreduce(codes, "tp")  # mxshard: allclose-ok(2-bit EF wire: +-1 int8 sign codes at wire_threshold on the wire; opt-in lossy envelope, exact paths keep fp32)
    return total.astype(y.dtype) * thr


def _logits_psum(y):
    """Weight-tied unembedding reduction: each member contracts its local
    hidden columns against its embedding shard; the psum completes the
    ``[.., V]`` logits.  Always exact fp32 — even under ``wire="2bit"``
    the argmax surface is never quantized."""
    from ...parallel import allreduce
    return allreduce(y, "tp")  # mxshard: allclose-ok(row-parallel tied-unembed reduction: member order differs from the serial sum; kept exact fp32 even under wire=2bit so the argmax surface is never quantized)


def _local_cols(geom, x):
    """This member's ``hidden/tp`` column slice of a replicated
    full-width activation (the row-parallel contraction input)."""
    import jax
    i = jax.lax.axis_index("tp")
    return jax.lax.dynamic_slice_in_dim(
        x, i * geom.hidden_local, geom.hidden_local, axis=x.ndim - 1)


def _qkv_local(geom, p, l, x, lead):
    """Column-parallel qkv: the replicated ``x`` against LOCAL column
    shards.  The contract reshape ``(rows, heads, dim)`` is head-major in
    columns, so member i's contiguous column block is exactly heads
    ``[i*local : (i+1)*local]`` — aligned with the pool's head shard, no
    collective between projection and cache write."""
    shape = tuple(lead) + (geom.local_heads, geom.head_dim)
    q = (x @ p["l%d_wq" % l]).reshape(shape)
    k = (x @ p["l%d_wk" % l]).reshape(shape)
    v = (x @ p["l%d_wv" % l]).reshape(shape)
    return q, k, v


def _mlp_block(geom, p, l, h):
    """Megatron MLP half-block: column-parallel up (w1), row-parallel
    down (w2), one psum."""
    import jax
    g = jax.nn.gelu(_rms(h) @ p["l%d_w1" % l])
    return h + _block_psum(geom, g @ p["l%d_w2" % l])


def _decode_step(geom, p, small, k_pool, v_pool):
    """Compute-parallel twin of TinyCausalLM.decode_fn: one fixed-shape
    token step per slot, head-local paged attention, 2 psums per layer."""
    import jax.numpy as jnp
    tokens, positions, tables = small
    bs = k_pool.shape[2]
    S = tokens.shape[0]
    W = tables.shape[1]
    T = W * bs
    srow = jnp.arange(S)
    h = _assemble_replicated(
        geom, p["embed"][tokens] + p["pos"][positions])        # [S, H]
    blk = tables[srow, positions // bs]
    off = positions % bs
    mask = jnp.arange(T)[None, :] <= positions[:, None]        # [S, T]
    for l in range(geom.num_layers):
        q, k, v = _qkv_local(geom, p, l, _rms(h), (S,))
        k_pool = k_pool.at[l, blk, off].set(k)
        v_pool = v_pool.at[l, blk, off].set(v)
        kseq = k_pool[l][tables].reshape(S, T, geom.local_heads,
                                         geom.head_dim)
        vseq = v_pool[l][tables].reshape(S, T, geom.local_heads,
                                         geom.head_dim)
        scores = jnp.einsum("shd,sthd->sht", q, kseq) \
            / jnp.sqrt(float(geom.head_dim)).astype(q.dtype)
        scores = jnp.where(mask[:, None, :], scores, -jnp.inf)
        w = _softmax(scores)
        att = jnp.einsum("sht,sthd->shd", w, vseq).reshape(
            S, geom.hidden_local)
        h = h + _block_psum(geom, att @ p["l%d_wo" % l])
        h = _mlp_block(geom, p, l, h)
    logits = _logits_psum(_local_cols(geom, _rms(h)) @ p["embed"].T)
    return logits, k_pool, v_pool


def _prefill(geom, p, small, k_pool, v_pool):
    """Compute-parallel twin of TinyCausalLM.prefill_fn: the whole padded
    prompt in one causal pass, local heads only."""
    import jax.numpy as jnp
    tokens, length, table = small
    bs = k_pool.shape[2]
    L = tokens.shape[1]
    t = tokens[0]
    h = _assemble_replicated(geom, p["embed"][t] + p["pos"][:L])
    idx = jnp.arange(L)
    blk = table[0, idx // bs]
    off = idx % bs
    causal = idx[None, :] <= idx[:, None]                      # [L, L]
    for l in range(geom.num_layers):
        q, k, v = _qkv_local(geom, p, l, _rms(h), (L,))
        k_pool = k_pool.at[l, blk, off].set(k)
        v_pool = v_pool.at[l, blk, off].set(v)
        scores = jnp.einsum("ihd,jhd->hij", q, k) \
            / jnp.sqrt(float(geom.head_dim)).astype(q.dtype)
        scores = jnp.where(causal[None], scores, -jnp.inf)
        w = _softmax(scores)
        att = jnp.einsum("hij,jhd->ihd", w, v).reshape(
            L, geom.hidden_local)
        h = h + _block_psum(geom, att @ p["l%d_wo" % l])
        h = _mlp_block(geom, p, l, h)
    last = _local_cols(geom, _rms(h[length[0] - 1]))
    logits = _logits_psum(last @ p["embed"].T)
    return logits[None], k_pool, v_pool


def _chunk_prefill(geom, p, small, k_pool, v_pool):
    """Compute-parallel twin of TinyCausalLM.chunk_prefill_fn: one prompt
    chunk at absolute positions, earlier chunks read from the local pool
    shard through the page table."""
    import jax.numpy as jnp
    tokens, start, length, table = small
    bs = k_pool.shape[2]
    C = tokens.shape[1]
    W = table.shape[1]
    T = W * bs
    t = tokens[0]
    pos = start[0] + jnp.arange(C)
    h = _assemble_replicated(
        geom, p["embed"][t]
        + p["pos"][jnp.clip(pos, 0, geom.max_len - 1)])
    blk = table[0, pos // bs]
    off = pos % bs
    valid = jnp.arange(C) < length[0]
    blk = jnp.where(valid, blk, 0)                     # pad -> trash
    epos = jnp.where(valid, pos, 0)
    mask = jnp.arange(T)[None, :] <= epos[:, None]     # [C, T]
    for l in range(geom.num_layers):
        q, k, v = _qkv_local(geom, p, l, _rms(h), (C,))
        k_pool = k_pool.at[l, blk, off].set(k)
        v_pool = v_pool.at[l, blk, off].set(v)
        kseq = k_pool[l][table[0]].reshape(T, geom.local_heads,
                                           geom.head_dim)
        vseq = v_pool[l][table[0]].reshape(T, geom.local_heads,
                                           geom.head_dim)
        scores = jnp.einsum("ihd,jhd->hij", q, kseq) \
            / jnp.sqrt(float(geom.head_dim)).astype(q.dtype)
        scores = jnp.where(mask[None], scores, -jnp.inf)
        w = _softmax(scores)
        att = jnp.einsum("hij,jhd->ihd", w, vseq).reshape(
            C, geom.hidden_local)
        h = h + _block_psum(geom, att @ p["l%d_wo" % l])
        h = _mlp_block(geom, p, l, h)
    last = _local_cols(geom, _rms(h[length[0] - 1]))
    logits = _logits_psum(last @ p["embed"].T)
    return logits[None], k_pool, v_pool


def _verify(geom, p, small, k_pool, v_pool):
    """Compute-parallel twin of TinyCausalLM.verify_fn: K+1 tokens per
    slot in one fixed-shape call, invalid rows to the trash block."""
    import jax.numpy as jnp
    tokens, positions, valids, tables = small
    bs = k_pool.shape[2]
    S, K1 = tokens.shape
    W = tables.shape[1]
    T = W * bs
    pos = positions[:, None] + jnp.arange(K1)[None, :]   # [S, K1]
    valid = jnp.arange(K1)[None, :] < valids[:, None]
    h = _assemble_replicated(
        geom, p["embed"][tokens]
        + p["pos"][jnp.clip(pos, 0, geom.max_len - 1)])  # [S, K1, H]
    blk = jnp.take_along_axis(tables, pos // bs, axis=1)
    blk = jnp.where(valid, blk, 0)                       # -> trash
    off = pos % bs
    epos = jnp.where(valid, pos, 0)
    mask = jnp.arange(T)[None, None, :] <= epos[:, :, None]
    for l in range(geom.num_layers):
        q, k, v = _qkv_local(geom, p, l, _rms(h), (S, K1))
        k_pool = k_pool.at[l, blk, off].set(k)
        v_pool = v_pool.at[l, blk, off].set(v)
        kseq = k_pool[l][tables].reshape(S, T, geom.local_heads,
                                         geom.head_dim)
        vseq = v_pool[l][tables].reshape(S, T, geom.local_heads,
                                         geom.head_dim)
        scores = jnp.einsum("sihd,sjhd->shij", q, kseq) \
            / jnp.sqrt(float(geom.head_dim)).astype(q.dtype)
        scores = jnp.where(mask[:, None, :, :], scores, -jnp.inf)
        w = _softmax(scores)
        att = jnp.einsum("shij,sjhd->sihd", w, vseq).reshape(
            S, K1, geom.hidden_local)
        h = h + _block_psum(geom, att @ p["l%d_wo" % l])
        h = _mlp_block(geom, p, l, h)
    logits = _logits_psum(_local_cols(geom, _rms(h)) @ p["embed"].T)
    return logits, k_pool, v_pool


def _propose_steps(geom, p, small, k_pool, v_pool, num_tokens):
    """Compute-parallel twin of TinyCausalLM.propose_fn: ``num_tokens``
    unrolled decode steps with the argmax on-device (logits are psum'd
    replicated, so the argmax is too)."""
    import jax.numpy as jnp
    tokens, positions, tables = small
    cur = tokens
    pos = positions
    outs = []
    for _ in range(int(num_tokens)):
        logits, k_pool, v_pool = _decode_step(
            geom, p, (cur, pos, tables), k_pool, v_pool)
        cur = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        outs.append(cur)
        pos = pos + 1
    return jnp.stack(outs, axis=1), k_pool, v_pool


def _sharded_kernel(geom, which, p, small, k_pool, v_pool):
    """Single inside-shard_map entry point for every contract fn.

    Called by literal name from the one region ``body`` so the whole
    kernel family — and each of the four static psum sites — lands in the
    mxshard/mxmem budget closure of
    ``ShardedDecodeModel._build_fn.body``."""
    p = _contract_local(geom, p)
    kind = which[0]
    if kind == "decode":
        return _decode_step(geom, p, small, k_pool, v_pool)
    if kind == "prefill":
        return _prefill(geom, p, small, k_pool, v_pool)
    if kind == "chunk_prefill":
        return _chunk_prefill(geom, p, small, k_pool, v_pool)
    if kind == "verify":
        return _verify(geom, p, small, k_pool, v_pool)
    if kind == "propose":
        return _propose_steps(geom, p, small, k_pool, v_pool, which[1])
    raise ValueError("unknown sharded kernel %r" % (which,))


# ---------------------------------------------------------------------------
# the sharded contract wrapper
# ---------------------------------------------------------------------------

class ShardedDecodeModel:
    """Run a decode-model contract compute-parallel over a ('tp','sp') mesh.

    Satisfies the full contract of the wrapped model (same attrs, same
    fn signatures, ``chunk_prefill_fn``/``verify_fn``/``propose_fn``
    present iff the inner model has them), so DecodeEngine, the prefix
    cache, speculative decode, export/import handoff and the sequential
    reference all compose unchanged — now shard-resident end to end.
    Three extra hooks the engine picks up when present:

    * ``zeros_pool(shape)`` — fresh head-sharded K/V pool storage;
    * ``place_inputs(x)`` — pins per-step host inputs replicated on the
      mesh (a jit call cannot mix single-device-committed and
      mesh-committed operands);
    * ``tp_degree`` / ``sp_degree`` — the fleet's device-footprint
      accounting (`FleetRouter.load_decode(..., tp=k)`).

    The wrapper requires the canonical decode parameter schema
    (``embed``/``pos`` plus per-layer ``wq wk wv wo w1 w2``) in either
    the contract layout (``[in, units]``, TinyCausalLM) or the Gluon
    layout (``[units, in]``, ``param_layout = "gluon"`` — the adapter);
    the kernels erase the difference by transposing local shards.

    Exported pages (`export_stream`) host-gather to the full head axis,
    so sharded→sharded and sharded→unsharded handoffs are geometry-free
    round trips; greedy/sampled token streams are identical across
    geometries (logits allclose under the documented psum relaxation).
    """

    def __init__(self, model, tp=2, sp=1, devices=None, wire=None,
                 wire_threshold=0.05):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ...ndarray import NDArray
        self._inner = model
        self.tp = int(tp)
        self.sp = int(sp)
        self.tp_degree = self.tp
        self.sp_degree = self.sp
        # contract geometry proxies (export/import geometry dicts and the
        # PagedKVCache pool grid come from these)
        self.vocab_size = model.vocab_size
        self.num_layers = model.num_layers
        self.num_heads = model.num_heads
        self.head_dim = model.head_dim
        self.max_len = model.max_len
        self.eos_id = getattr(model, "eos_id", None)
        self._local_heads = check_tp_divisible(
            type(model).__name__, model.num_heads, self.tp)
        if wire not in (None, "2bit"):
            raise ValueError(
                "ShardedDecodeModel: unknown wire %r (supported: None "
                "for exact fp32 psums, '2bit' for the quantized codec)"
                % (wire,))
        self.wire = wire
        self.wire_threshold = float(wire_threshold)
        if self.wire == "2bit" and not self.wire_threshold > 0:
            raise ValueError(
                "ShardedDecodeModel: wire='2bit' needs wire_threshold "
                "> 0, got %r" % (wire_threshold,))
        if getattr(model, "context_attention", None) is not None:
            raise ValueError(
                "ShardedDecodeModel: inner model sets "
                "context_attention=%r, but the compute-parallel kernels "
                "run head-local attention and do not route the fused "
                "long-context path; serve this model unsharded or clear "
                "context_attention" % (model.context_attention,))
        self.mesh = decode_mesh(self.tp, self.sp, devices)
        if int(self.mesh.shape["tp"]) != self.tp:
            raise ValueError(
                "ShardedDecodeModel: mesh 'tp' extent %d does not match "
                "the requested tp degree %d"
                % (int(self.mesh.shape["tp"]), self.tp))
        # no trailing None: shard_map normalizes its out_specs that way,
        # and jit's executable cache keys on sharding EQUALITY — a fresh
        # zeros_pool must carry the byte-same sharding as a pool carried
        # out of a step, or the first post-warmup step stealth-recompiles
        self._pool_sharding = NamedSharding(
            self.mesh, P(None, None, None, "tp"))
        self._replicated = NamedSharding(self.mesh, P())

        # resolve one PartitionSpec per parameter and place the weights
        raw = {}
        if hasattr(model, "partition_specs"):
            raw = dict(model.partition_specs())
        inner_params = model.param_dict()
        self._pspecs = {}
        self._params = {}
        for name in sorted(inner_params):
            spec = self._check_spec(name, raw.get(name),
                                    inner_params[name].shape)
            self._pspecs[name] = spec
            self._params[name] = NDArray(jax.device_put(
                inner_params[name]._data, NamedSharding(self.mesh, spec)))

        gluon = getattr(model, "param_layout", "contract") == "gluon"
        self._validate_canonical(inner_params, gluon)
        self._geom = _Geometry(
            num_layers=self.num_layers, num_heads=self.num_heads,
            local_heads=self._local_heads, head_dim=self.head_dim,
            hidden=self.num_heads * self.head_dim,
            hidden_local=(self.num_heads * self.head_dim) // self.tp,
            vocab_size=self.vocab_size, max_len=self.max_len,
            tp=self.tp, gluon=gluon, wire=self.wire,
            wire_threshold=self.wire_threshold)

        self._prefill_sm = self._build_fn(("prefill",), 3)
        self._decode_sm = self._build_fn(("decode",), 3)
        if hasattr(model, "chunk_prefill_fn"):
            self._chunk_sm = self._build_fn(("chunk_prefill",), 4)
            self.chunk_prefill_fn = self._make_call(self._chunk_sm, 4)
        if hasattr(model, "verify_fn"):
            self._verify_sm = self._build_fn(("verify",), 4)
            self.verify_fn = self._make_call(self._verify_sm, 4)
        if hasattr(model, "propose_fn"):
            self._propose_sms = {}
            self.propose_fn = self._propose_call

    # -- contract surface ------------------------------------------------
    def param_dict(self):
        """Live mesh-sharded parameter handles (same-name contract)."""
        return dict(self._params)

    def prefill_fn(self, p, tokens, length, table, k_pool, v_pool):
        return self._prefill_sm(p, (tokens, length, table), k_pool, v_pool)

    def decode_fn(self, p, tokens, positions, tables, k_pool, v_pool):
        return self._decode_sm(p, (tokens, positions, tables), k_pool,
                               v_pool)

    def _propose_call(self, p, tokens, positions, tables, k_pool, v_pool,
                      num_tokens):
        sm = self._propose_sms.get(int(num_tokens))
        if sm is None:
            sm = self._build_fn(("propose", int(num_tokens)), 3)
            self._propose_sms[int(num_tokens)] = sm
        return sm(p, (tokens, positions, tables), k_pool, v_pool)

    # -- engine hooks ----------------------------------------------------
    def zeros_pool(self, shape):
        """Fresh zeroed head-sharded pool storage for ``shape`` (the
        contract layout; the head axis must divide tp)."""
        import jax
        import jax.numpy as jnp
        from ...ndarray import NDArray
        check_pool_matches_mesh(type(self._inner).__name__, shape,
                                self.mesh)
        return NDArray(jax.device_put(jnp.zeros(shape, jnp.float32),
                                      self._pool_sharding))

    def place_inputs(self, x):
        """Pin a per-step operand on the serving mesh (replicated) unless
        it already lives there; mesh-resident pools/params pass through
        untouched so their shardings stay byte-stable across steps."""
        import jax
        from jax.sharding import NamedSharding
        sh = getattr(x, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.mesh == self.mesh:
            return x
        return jax.device_put(x, self._replicated)

    # -- internals -------------------------------------------------------
    def _check_spec(self, name, spec, shape):
        """Validate a parameter PartitionSpec eagerly: only the 'tp' axis,
        one axis name per dim, and the dim must divide the extent."""
        from jax.sharding import PartitionSpec as P
        if spec is None:
            return P()
        entries = tuple(spec)
        if len(entries) > len(shape):
            raise ValueError(
                "%s: partition spec %r has %d entries for a rank-%d "
                "parameter" % (name, spec, len(entries), len(shape)))
        for dim, ax in enumerate(entries):
            if ax is None:
                continue
            if ax != "tp":
                raise ValueError(
                    "%s: partition spec %r names axis %r; decode weight "
                    "sharding supports only the 'tp' mesh axis"
                    % (name, spec, ax))
            check_tp_divisible(name, shape[dim], self.tp,
                               what="dim %d extent" % dim)
        return P(*entries)

    def _validate_canonical(self, inner_params, gluon):
        """The compute-parallel kernels are written against the canonical
        decode schema; verify roles, shapes and the Megatron spec pattern
        eagerly so mismatches raise here, never inside shard_map."""
        name = type(self._inner).__name__
        hid = self.num_heads * self.head_dim
        want = {"embed", "pos"}
        for l in range(self.num_layers):
            want |= {"l%d_%s" % (l, r) for r in _DENSE_ROLES}
        have = set(inner_params)
        if have != want:
            raise ValueError(
                "%s: parameter roles do not match the canonical decode "
                "schema the compute-parallel kernels require (missing %s, "
                "unexpected %s)"
                % (name, sorted(want - have) or "none",
                   sorted(have - want) or "none"))
        # shapes per layout; the sharded dim per role per layout
        col = ("wq", "wk", "wv", "w1")
        shapes = {"embed": (self.vocab_size, hid),
                  "pos": (self.max_len, hid)}
        specs = {"embed": (None, "tp"), "pos": (None, "tp")}
        for l in range(self.num_layers):
            for r in ("wq", "wk", "wv", "wo"):
                shapes["l%d_%s" % (l, r)] = (hid, hid)
            if gluon:
                shapes["l%d_w1" % l] = (2 * hid, hid)
                shapes["l%d_w2" % l] = (hid, 2 * hid)
            else:
                shapes["l%d_w1" % l] = (hid, 2 * hid)
                shapes["l%d_w2" % l] = (2 * hid, hid)
            for r in _DENSE_ROLES:
                col_role = (r in col) != bool(gluon)
                specs["l%d_%s" % (l, r)] = ((None, "tp") if col_role
                                            else ("tp",))
        for pname in sorted(want):
            got_shape = tuple(inner_params[pname].shape)
            if got_shape != shapes[pname]:
                raise ValueError(
                    "%s: parameter %r has shape %r; the %s layout of the "
                    "canonical decode schema requires %r"
                    % (name, pname, got_shape,
                       "gluon" if gluon else "contract", shapes[pname]))
            got = tuple(self._pspecs[pname])
            while got and got[-1] is None:
                got = got[:-1]
            if got != specs[pname]:
                raise ValueError(
                    "%s: parameter %r has partition spec %r; the "
                    "compute-parallel Megatron kernels require %r for the "
                    "%s layout"
                    % (name, pname, tuple(self._pspecs[pname]),
                       specs[pname], "gluon" if gluon else "contract"))

    def _build_fn(self, which, n_small):
        """shard_map one compute-parallel kernel: weights and K/V stay on
        their shards, each Megatron half-block ends in its single psum,
        and the kernels write the LOCAL head slice of the pool carries
        directly — no gather, no slice-back."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        geom = self._geom
        pool_spec = P(None, None, None, "tp")
        pspecs = dict(self._pspecs)

        # The decode step's collective bill: one exact scatter-assembly
        # psum, two Megatron block psums per layer, one tied-unembed psum
        # — 2*num_layers + 2 psum calls, ZERO gathers.  Four static psum
        # sites back those calls (assembly / block / 2bit-wire / unembed).
        # The declared worst case under the accountant's reuse-free model
        # is the psum outputs live at once — predict_decode_step_peak_bytes()
        # is the exact symbolic form, pinned == the runtime peak in
        # BENCH_SHARDED_DECODE.json.
        # mxmem: budget(hbm=64MB)
        # mxshard: budget(psum=4)
        def body(p_local, small, k_local, v_local):
            return _sharded_kernel(geom, which, p_local, small, k_local,
                                   v_local)

        return shard_map(
            body, mesh=self.mesh,
            in_specs=(pspecs, tuple(P() for _ in range(n_small)),
                      pool_spec, pool_spec),
            out_specs=(P(), pool_spec, pool_spec),
            check_rep=False)

    @staticmethod
    def _make_call(sm, n_small):
        def call(p, *args):
            return sm(p, tuple(args[:n_small]), args[n_small],
                      args[n_small + 1])
        return call
