"""DecodeEngine: continuous batching for autoregressive generation.

The MicroBatcher (batcher.py) batches at *request* granularity — right for
one-shot inference, wrong for generation, where requests are hundreds of
decode steps long and finish at different times: request-level batching
leaves slots idle from each sequence's last token until the batch's last.
This engine schedules at **iteration** granularity: every decode step,
finished sequences leave their slot and queued requests join, so the
fixed-shape step stays as full as admission allows (the TensorFlow paper's
production lesson — the serving runtime, not the model, decides whether the
hardware stays busy).

Fixed shapes, zero steady-state recompiles (the XLA contract, same as the
bucket ladder in buckets.py):

* the decode step is always ``[max_slots]`` wide — join/leave changes slot
  *contents*, never the signature; dead slots compute garbage against the
  trash block and are masked host-side;
* the attention width (page-table columns) is bucketed: the scheduler picks
  the smallest precompiled width covering the longest live sequence, so
  signatures = width buckets, all warmed at load;
* prefill runs separately through a prompt-length bucket ladder
  (``buckets.BucketLadder`` reuse) — one ``[1, Lb]`` causal pass per
  joining request that populates its KV pages and yields the first token
  (the TTFT token), keeping long-prompt compute out of the per-token step.

KV memory is a paged block pool (kv_cache.py): admission reserves the
worst-case block count (shedding OVERLOADED when the pool cannot honor
it), blocks are allocated lazily as sequences grow and freed the moment a
sequence finishes.

Four opt-in throughput multipliers stack on that core (each off by
default, leaving the base engine bit-identical):

* ``prefill_chunk=C`` — prompts prefill in fixed ``[1, C]`` chunks, ONE
  chunk per scheduler iteration, interleaved with decode steps: a long
  prompt no longer stalls live streams' TTFT.  One chunk signature
  replaces the prompt bucket ladder (same-shape kernels are what keep the
  chunked path bitwise-reproducible), and ``generate_reference`` chunks
  identically.
* ``prefix_cache=True`` (requires ``prefill_chunk``) — ``reserve()``
  attaches the longest registered shared prompt prefix (kv_cache.py chain
  hashes), prefill skips straight to the first unshared chunk, and writes
  into shared pages copy-on-write fork first (device pages copied, table
  entry swapped).  A fleet-wide shared system prompt costs one prefill.
* ``temperature``/``top_k``/``top_p``/``seed`` on ``submit()`` — seeded
  host-side sampling (sampling.py): greedy stays the default and sampled
  streams replay exactly (same seed => same tokens) across restarts and
  handoffs.
* ``spec_k=K, draft_model=...`` (requires ``prefill_chunk``) — a draft
  model proposes K greedy tokens in one unrolled call, ONE paged verify
  step scores K+1 positions, and the engine commits the longest agreeing
  prefix: up to K+1 tokens for two dispatches.  Emitted tokens depend
  only on the *target* logits chain, so speculative greedy output is
  bitwise-equal to the sequential reference no matter what the draft
  proposes — the draft can be wrong, stale, or freshly imported garbage
  and only the acceptance rate moves.

``prefill_only=True`` (requires ``prefill_chunk``, excludes speculation)
turns the engine into one tier of a DISAGGREGATED deployment
(serving/disagg/): it runs chunked prefill, emits the TTFT token, and
then — instead of decoding — hands the stream off through the sink
installed with :meth:`set_handoff` (the same snapshot dict
``export_stream`` produces: K/V pages, cursor, sampler state).  KV
admission reserves only the PROMPT's blocks (no decode growth happens
here), so the same pool admits far more concurrent prefills, and the
decode-width signatures are neither warmed nor ever dispatched.

Every request is a :class:`DecodeStream` — tokens stream out as they are
produced (iterator and/or ``on_token`` callback), and the terminal state
is a status, never an exception: the same vocabulary as server.py
(OK / TIMEOUT / OVERLOADED / INVALID_INPUT / ERROR / UNAVAILABLE), with
the deadline, bounded-admission, and circuit-breaker machinery
(health.py) applied per-stream.  docs/SERVING.md#autoregressive-decode
has the operator's view.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque

import numpy as np

from ... import autograd
from ... import faults
from ... import util
from ...base import MXNetError
from ...cached_op import CachedOp
from ..buckets import BucketLadder
from ..health import CircuitBreaker, PROBE, REJECT
from ..server import (OK, TIMEOUT, OVERLOADED, INVALID_INPUT, ERROR,
                      UNAVAILABLE)
from .kv_cache import PagedKVCache
from .sampling import SamplingParams, StreamSampler
from .stats import DecodeStats

__all__ = ["DecodeEngine", "DecodeStream"]

# transient-retry envelope around one prefill/decode execution, matching
# ServableModel's policy (docs/ROBUSTNESS.md)
_EXEC_ATTEMPTS = 3
_EXEC_BACKOFF_S = 0.002


class DecodeStream:
    """One autoregressive request: async handle + incremental token stream.

    Tokens arrive via :meth:`tokens` / iteration / the ``on_token``
    callback as the engine produces them; ``wait()`` blocks until the
    terminal status is set.  Because a stream is incremental, a TIMEOUT
    or UNAVAILABLE terminal keeps the tokens already emitted — the status
    says why the stream *ended*, not that its prefix is invalid.
    """

    def __init__(self, prompt, max_new_tokens, deadline=None, stats=None,
                 on_token=None, sampling=None):
        self.prompt = prompt                 # int32 numpy copy
        self.max_new_tokens = max_new_tokens
        self.deadline = deadline             # monotonic seconds or None
        self.stats = stats                   # engine DecodeStats handle
        self.sampling = sampling             # SamplingParams or None=greedy
        self.seq_id = None                   # assigned at submission
        self.admitted = False
        self.t_submit = time.monotonic()
        self._on_token = on_token
        self._cond = threading.Condition()
        self._tokens = []
        self._owner = None          # fencing token; None = unfenced
        self._on_terminal = None    # router hook, fired once off-lock
        self.status = None
        self.error = None
        self.ttft_ms = None
        self.latency_ms = None

    def expired(self, now=None):
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                >= self.deadline)

    # -- fencing ---------------------------------------------------------
    def set_owner(self, token):
        """Install the fencing token (router: ``(rid, lease_generation)``).
        Emissions and owner-checked completions presenting a different
        token are refused — the zombie-replica double-emit guard."""
        with self._cond:
            self._owner = token

    def owner(self):
        with self._cond:
            return self._owner

    def on_terminal(self, cb):
        """Register a one-shot terminal hook ``cb(stream)``; fires off-lock
        right after the winning ``complete()`` — or immediately, if the
        stream is already terminal (registration/completion race-safe)."""
        with self._cond:
            if self.status is None:
                self._on_terminal = cb
                return
        cb(self)

    # -- engine side ----------------------------------------------------
    def _emit(self, token, owner=None):
        with self._cond:
            if self.status is not None:
                return          # terminal already claimed; drop the token
            if self._owner is not None and owner != self._owner:
                return          # fenced: only the owning engine may emit
            if self.ttft_ms is None:
                self.ttft_ms = (time.monotonic() - self.t_submit) * 1e3
            self._tokens.append(int(token))
            self._cond.notify_all()
        cb = self._on_token
        if cb is not None:
            # outside the lock: user code must not block token delivery or
            # nest our cond; a raising callback is disabled (the stream
            # keeps generating — delivery is best-effort, wait()/tokens()
            # stay authoritative)
            try:
                cb(int(token))
            except Exception:
                self._on_token = None

    def complete(self, status, error=None, owner=None):
        """First completion wins (engine finish vs teardown vs expiry).

        An *owner-checked* completion (``owner`` non-None on a fenced
        stream) is refused on mismatch — a stale engine draining after a
        handoff cannot terminate the stream out from under its new home.
        ``owner=None`` always passes: unfenced callers (direct engine use,
        client-side cancels) predate fencing and stay valid."""
        cb = None
        with self._cond:
            if self.status is not None:
                return False
            if (self._owner is not None and owner is not None
                    and owner != self._owner):
                return False    # fenced: a non-owner may not terminate
            self.error = error
            self.latency_ms = (time.monotonic() - self.t_submit) * 1e3
            # status last: it is the done flag every reader keys on
            self.status = status
            self._cond.notify_all()
            cb = self._on_terminal
            self._on_terminal = None
        if cb is not None:
            # off-lock, like on_token: the router's hook takes its own
            # lock and must never nest inside the stream's cond
            try:
                cb(self)
            except Exception:
                pass
        return True

    # -- client side ----------------------------------------------------
    def tokens(self):
        """Snapshot of the tokens emitted so far."""
        with self._cond:
            return list(self._tokens)

    def wait(self, timeout=None):
        """Block until terminal; returns True when a status is set."""
        with self._cond:
            return self._cond.wait_for(lambda: self.status is not None,
                                       timeout)

    def result(self):
        """Wait the stream out and return it (fluent blocking read)."""
        self.wait()
        return self

    def snapshot(self):
        """Atomic (status, tokens, ttft_ms, latency_ms, error)."""
        with self._cond:
            return (self.status, tuple(self._tokens), self.ttft_ms,
                    self.latency_ms, self.error)

    def __iter__(self):
        """Yield tokens as they arrive; stops when the stream is terminal
        and drained.  Check ``status`` afterwards for why it ended."""
        i = 0
        while True:
            with self._cond:
                self._cond.wait_for(
                    lambda: len(self._tokens) > i or self.status is not None)
                if len(self._tokens) <= i:
                    return
                tok = self._tokens[i]
            i += 1
            yield tok

    def __repr__(self):
        status, toks, ttft, lat, err = self.snapshot()
        return ("DecodeStream(status=%s, tokens=%d%s%s)"
                % (status, len(toks),
                   ", ttft_ms=%.2f" % ttft if ttft is not None else "",
                   ", error=%r" % err if err else ""))


class _QEntry:
    """One queued admission: the stream, its fencing token, and — for
    streams entering via ``import_stream`` — the KV snapshot to restore
    at join instead of running a prefill."""

    __slots__ = ("stream", "gen", "snap")

    def __init__(self, stream, gen=None, snap=None):
        self.stream = stream
        self.gen = gen
        self.snap = snap


class _Seq:
    """Engine-private per-slot state for one live sequence."""

    __slots__ = ("stream", "seq_id", "position", "cur_token", "generated",
                 "gen", "snap", "prefill_pos", "sampler")

    def __init__(self, stream, gen=None, snap=None):
        self.stream = stream
        self.seq_id = stream.seq_id
        self.position = 0       # cache index the next K/V write lands at
        self.cur_token = 0      # last emitted token (next step's input)
        self.generated = 0
        self.gen = gen          # fencing token presented on emit/complete
        self.snap = snap        # pending import restore, cleared at resume
        self.prefill_pos = None  # next prompt position to chunk-prefill
        self.sampler = None     # StreamSampler when the stream samples


class DecodeEngine:
    """Continuous-batching decode loop over one decode-capable model."""

    def __init__(self, model, name="decode", max_slots=8, block_size=8,
                 num_blocks=None, max_prompt_len=16, max_new_tokens=32,
                 max_queue=64, scheduling="continuous", width_blocks=None,
                 warmup=True, breaker_threshold=5, breaker_backoff_ms=50.0,
                 breaker_max_backoff_ms=2000.0, prefill_chunk=None,
                 prefix_cache=False, spec_k=0, draft_model=None,
                 prefill_only=False, generation=None):
        if scheduling not in ("continuous", "static"):
            raise ValueError("scheduling must be 'continuous' or 'static'")
        self.name = name
        self.model = model
        # weight generation tag (serving/deploy.py): which checkpoint epoch
        # this engine's params came from.  None = untagged (standalone use).
        # import_stream refuses snapshots from a different generation — a
        # stream must finish against the weights it started on
        # (docs/CONCURRENCY.md invariant 13).
        self.generation = generation
        self.scheduling = scheduling
        self.max_slots = int(max_slots)
        self.max_prompt_len = int(max_prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self._max_queue = int(max_queue)
        self.prefill_chunk = int(prefill_chunk) if prefill_chunk else None
        self.prefix_cache = bool(prefix_cache)
        self.spec_k = int(spec_k)
        self.draft = draft_model
        self.prefill_only = bool(prefill_only)
        self._handoff_cb = None     # set_handoff sink (prefill_only)
        if self.prefill_only and self.prefill_chunk is None:
            raise ValueError("prefill_only requires prefill_chunk (the "
                             "prefill tier runs the chunked path)")
        if self.prefill_only and self.spec_k > 0:
            raise ValueError("prefill_only excludes speculative decoding "
                             "(no decode steps run on the prefill tier)")
        if self.prefill_chunk is not None:
            if self.prefill_chunk <= 0 \
                    or self.prefill_chunk % int(block_size):
                raise ValueError("prefill_chunk must be a positive multiple "
                                 "of block_size, got %r" % (prefill_chunk,))
        if self.prefix_cache and self.prefill_chunk is None:
            raise ValueError("prefix_cache requires prefill_chunk (shared "
                             "prefixes attach at chunk boundaries)")
        if (self.spec_k > 0) != (draft_model is not None):
            raise ValueError("speculative decoding needs both spec_k > 0 "
                             "and a draft_model")
        if self.spec_k > 0 and self.prefill_chunk is None:
            raise ValueError("speculative decoding requires prefill_chunk "
                             "(the draft prefills through the chunk path)")
        max_total = self.max_prompt_len + self.max_new_tokens
        if max_total > model.max_len:
            raise ValueError(
                "max_prompt_len + max_new_tokens = %d exceeds the model's "
                "max_len %d" % (max_total, model.max_len))
        if draft_model is not None:
            if draft_model.vocab_size != model.vocab_size:
                raise ValueError("draft vocab %d != target vocab %d"
                                 % (draft_model.vocab_size,
                                    model.vocab_size))
            if max_total > draft_model.max_len:
                raise ValueError("draft max_len %d cannot cover %d tokens"
                                 % (draft_model.max_len, max_total))
        # width ladder: page-table columns per decode signature.
        # ``width_blocks`` overrides the powers-of-2 default — e.g.
        # ``[engine.worst_case_width(...)]`` trades the narrow-width fast
        # path for a single decode signature (and a scheduler-independent
        # per-step cost; tools/serve_bench.py does exactly that)
        max_width = self.worst_case_width(self.max_prompt_len,
                                          self.max_new_tokens, block_size)
        if self.spec_k > 0:
            # the draft's unrolled proposals write up to spec_k positions
            # past the committed cursor; the table must index them without
            # clamping into a neighbor's entry
            max_width += -(-self.spec_k // int(block_size))
        self._width_ladder = BucketLadder(max_width, width_blocks)
        if self._width_ladder.max_batch < max_width:
            raise ValueError("width_blocks %r cannot cover a worst-case "
                             "sequence (%d blocks)"
                             % (width_blocks, max_width))
        self._prompt_ladder = BucketLadder(self.max_prompt_len)
        if num_blocks is None:
            # full occupancy at worst case: admission is then slot-bound
            num_blocks = self.max_slots * max_width + 1
        self._cache = PagedKVCache(model.num_layers, num_blocks, block_size,
                                   model.num_heads, model.head_dim,
                                   account_region="kv:%s" % name)
        self._params = model.param_dict()
        # mesh footprint: a sharded model (sharding.py) spans tp devices;
        # the fleet's placement and scaling advice count them through here
        self.tp_degree = int(getattr(model, "tp_degree", 1))
        self.stats = DecodeStats(name, kv_capacity=self._cache.capacity(),
                                 tp_degree=self.tp_degree)
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_threshold,
            backoff_s=breaker_backoff_ms / 1e3,
            max_backoff_s=breaker_max_backoff_ms / 1e3)
        # a mesh-sharded model (sharding.py) pins operand placement per
        # dispatch; unsharded models leave the hook absent and the flag
        # costs nothing
        mflags = self._placement_flags(model)
        dflags = self._placement_flags(draft_model)
        self._prefill_cop = CachedOp(self._prefill_forward, self._params,
                                     flags=mflags)  # mxmem: nodonate(K/V pools are threaded functionally and re-read for export/handoff; donating would alias live pages)
        self._decode_cop = CachedOp(self._decode_forward, self._params,
                                    flags=mflags)  # mxmem: nodonate(pool handles outlive the step: export_stream and bitwise replay re-read them after dispatch)
        retry = util.retry(attempts=_EXEC_ATTEMPTS, backoff=_EXEC_BACKOFF_S,
                           on_retry=lambda exc, i: self.stats.on_retry())
        self._prefill_exec = retry(self._prefill_once)
        self._decode_exec = retry(self._decode_once)
        self._chunk_cop = self._chunk_exec = None
        if self.prefill_chunk is not None:
            self._chunk_cop = CachedOp(self._chunk_forward, self._params,
                                       flags=mflags)  # mxmem: nodonate(chunked prefill re-enters with the same pools across chunks; donation would free them mid-prompt)
            self._chunk_exec = retry(self._chunk_once)
        self._verify_cop = self._verify_exec = None
        self._draft_cop = self._draft_exec = None
        self._draft_chunk_cop = self._draft_chunk_exec = None
        self._draft_params = None
        self._dpools = None      # [draft k_pool, draft v_pool], worker-only
        if self.spec_k > 0:
            self._draft_params = draft_model.param_dict()
            self._verify_cop = CachedOp(self._verify_forward, self._params,
                                        flags=mflags)  # mxmem: nodonate(verify reads the same pools the decode path owns; rejected drafts roll back to them)
            self._verify_exec = retry(self._verify_once)
            self._draft_cop = CachedOp(self._draft_forward,
                                       self._draft_params, flags=dflags)  # mxmem: nodonate(draft pools persist across speculation rounds and rollbacks)
            self._draft_exec = retry(self._draft_once)
            self._draft_chunk_cop = CachedOp(self._draft_chunk_forward,
                                             self._draft_params,
                                             flags=dflags)  # mxmem: nodonate(draft prefill shares the draft pools with the per-round draft loop)
            self._draft_chunk_exec = retry(self._draft_chunk_once)
        self.warmup_report = None
        if warmup:
            self.warmup()
        self._cond = threading.Condition()
        # guarded by _cond: queue, slots, lifecycle flags; seq ids come
        # from an itertools.count (atomic at the C level, no lock needed)
        self._queue = deque()      # of _QEntry
        self._slots = [None] * self.max_slots
        self._running = True
        self._closed = False
        self._draining = False     # admission closed, worker parking
        self._quiesced = threading.Event()  # worker parked, pools published
        self._pools = None         # (k_pool, v_pool) while quiesced
        self._seq_counter = itertools.count()
        self._thread = threading.Thread(
            target=self._run, name="mx-decode-%s" % name, daemon=True)
        self._thread.start()

    @staticmethod
    def worst_case_width(max_prompt_len, max_new_tokens, block_size):
        """Page-table width (blocks) covering a worst-case sequence plus
        the one-block write slack: a finished sequence's last token is
        never written, but a mid-stream one landing exactly on a block
        boundary needs the next block before its attention window does."""
        return -(-(int(max_prompt_len) + int(max_new_tokens))
                 // int(block_size)) + 1

    # -- CachedOp forwards (NDArray in/out; pure jnp inside) -------------
    def _prefill_forward(self, params, tokens, length, table, k_pool,
                         v_pool):
        from ...ndarray import NDArray
        p = {n: a._data for n, a in params.items()}
        logits, kp, vp = self.model.prefill_fn(
            p, tokens._data, length._data, table._data, k_pool._data,
            v_pool._data)
        return [NDArray(logits), NDArray(kp), NDArray(vp)]

    def _decode_forward(self, params, tokens, positions, tables, k_pool,
                        v_pool):
        from ...ndarray import NDArray
        p = {n: a._data for n, a in params.items()}
        logits, kp, vp = self.model.decode_fn(
            p, tokens._data, positions._data, tables._data, k_pool._data,
            v_pool._data)
        return [NDArray(logits), NDArray(kp), NDArray(vp)]

    # -- execution (retry envelope + fault point, like ServableModel) ---
    def _prefill_once(self, tokens, length, table, k_pool, v_pool):
        from ... import ndarray as nd
        faults.fault_point("serving.predict", model=self.name)
        with autograd.pause():
            return self._prefill_cop(
                self._params, nd.array(tokens, dtype="int32"),
                nd.array(length, dtype="int32"),
                nd.array(table, dtype="int32"), k_pool, v_pool)

    def _decode_once(self, tokens, positions, tables, k_pool, v_pool):
        from ... import ndarray as nd
        faults.fault_point("serving.predict", model=self.name)
        with autograd.pause():
            return self._decode_cop(
                self._params, nd.array(tokens, dtype="int32"),
                nd.array(positions, dtype="int32"),
                nd.array(tables, dtype="int32"), k_pool, v_pool)

    # chunked prefill / speculative forwards: every one a FIXED shape —
    # [1, C] chunk, [S, K+1] verify, [S] draft — so turning the features
    # on adds a handful of warm signatures, never a steady-state compile
    def _chunk_forward(self, params, tokens, start, length, table, k_pool,
                       v_pool):
        from ...ndarray import NDArray
        p = {n: a._data for n, a in params.items()}
        logits, kp, vp = self.model.chunk_prefill_fn(
            p, tokens._data, start._data, length._data, table._data,
            k_pool._data, v_pool._data)
        return [NDArray(logits), NDArray(kp), NDArray(vp)]

    def _chunk_once(self, tokens, start, length, table, k_pool, v_pool):
        from ... import ndarray as nd
        faults.fault_point("serving.predict", model=self.name)
        with autograd.pause():
            return self._chunk_cop(
                self._params, nd.array(tokens, dtype="int32"),
                nd.array(start, dtype="int32"),
                nd.array(length, dtype="int32"),
                nd.array(table, dtype="int32"), k_pool, v_pool)

    def _verify_forward(self, params, tokens, positions, valids, tables,
                        k_pool, v_pool):
        from ...ndarray import NDArray
        p = {n: a._data for n, a in params.items()}
        logits, kp, vp = self.model.verify_fn(
            p, tokens._data, positions._data, valids._data, tables._data,
            k_pool._data, v_pool._data)
        return [NDArray(logits), NDArray(kp), NDArray(vp)]

    def _verify_once(self, tokens, positions, valids, tables, k_pool,
                     v_pool):
        from ... import ndarray as nd
        faults.fault_point("serving.predict", model=self.name)
        with autograd.pause():
            return self._verify_cop(
                self._params, nd.array(tokens, dtype="int32"),
                nd.array(positions, dtype="int32"),
                nd.array(valids, dtype="int32"),
                nd.array(tables, dtype="int32"), k_pool, v_pool)

    def _draft_forward(self, params, tokens, positions, tables, k_pool,
                       v_pool):
        from ...ndarray import NDArray
        p = {n: a._data for n, a in params.items()}
        props, kp, vp = self.draft.propose_fn(
            p, tokens._data, positions._data, tables._data, k_pool._data,
            v_pool._data, self.spec_k)
        return [NDArray(props), NDArray(kp), NDArray(vp)]

    def _draft_once(self, tokens, positions, tables, k_pool, v_pool):
        from ... import ndarray as nd
        faults.fault_point("serving.predict", model=self.name)
        with autograd.pause():
            return self._draft_cop(
                self._draft_params, nd.array(tokens, dtype="int32"),
                nd.array(positions, dtype="int32"),
                nd.array(tables, dtype="int32"), k_pool, v_pool)

    def _draft_chunk_forward(self, params, tokens, start, length, table,
                             k_pool, v_pool):
        from ...ndarray import NDArray
        p = {n: a._data for n, a in params.items()}
        logits, kp, vp = self.draft.chunk_prefill_fn(
            p, tokens._data, start._data, length._data, table._data,
            k_pool._data, v_pool._data)
        return [NDArray(logits), NDArray(kp), NDArray(vp)]

    def _draft_chunk_once(self, tokens, start, length, table, k_pool,
                          v_pool):
        from ... import ndarray as nd
        faults.fault_point("serving.predict", model=self.name)
        with autograd.pause():
            return self._draft_chunk_cop(
                self._draft_params, nd.array(tokens, dtype="int32"),
                nd.array(start, dtype="int32"),
                nd.array(length, dtype="int32"),
                nd.array(table, dtype="int32"), k_pool, v_pool)

    @staticmethod
    def _placement_flags(model):
        place = getattr(model, "place_inputs", None)
        return {"place_inputs": place} if place is not None else None

    @staticmethod
    def _zeros_pools(model, shape):
        """A pair of fresh zeroed pools for ``shape``; a sharded model
        places them head-sharded over its mesh (sharding.py), the default
        is plain device zeros."""
        zeros = getattr(model, "zeros_pool", None)
        if zeros is not None:
            return [zeros(shape), zeros(shape)]
        from ... import ndarray as nd
        return [nd.zeros(shape, dtype="float32"),
                nd.zeros(shape, dtype="float32")]

    def _record_pools(self, pools, shape):
        """Charge a freshly materialized K/V pool set to the engine's pool
        region (``<account_region>:pools``): ``prod(shape)`` fp32 words per
        pool.  Pool sets either live for the engine's lifetime or are
        warmup/reference throwaways, so the region only allocates — its
        alloc_bytes is the total pool traffic the engine ever charged."""
        from ... import memory_accounting
        nbytes = 1
        for d in shape:
            nbytes *= int(d)
        nbytes *= 4 * len(pools)   # fp32 pools
        memory_accounting.record_alloc(
            nbytes, "%s:pools" % self._cache.account_region,
            count=len(pools))
        return pools

    def _init_pools(self):
        """Fresh target-model K/V pools on the model's placement."""
        shape = self._cache.pool_shape()
        if getattr(self.model, "zeros_pool", None) is None:
            return self._record_pools(self._cache.init_pools(), shape)
        return self._record_pools(self._zeros_pools(self.model, shape),
                                  shape)

    def _draft_pools(self):
        """Fresh zeroed draft-model K/V pools (same block grid as the
        target pools, draft head geometry)."""
        shape = (self.draft.num_layers, self._cache.num_blocks,
                 self._cache.block_size, self.draft.num_heads,
                 self.draft.head_dim)
        return self._record_pools(self._zeros_pools(self.draft, shape),
                                  shape)

    # -- warmup ----------------------------------------------------------
    def warmup(self):
        """Precompile every prefill (prompt bucket) and decode (width
        bucket) signature against throwaway pools.  Steady-state traffic
        then never misses: ``cache_stats()`` must stay flat."""
        before = self.cache_stats()["misses"]
        k_pool, v_pool = self._init_pools()
        max_w = self._width_ladder.max_batch
        n = 0
        if self.prefill_chunk is not None:
            # one chunk signature replaces the whole prompt ladder
            outs = self._chunk_exec(
                np.zeros((1, self.prefill_chunk), np.int32),
                np.zeros((1,), np.int32), np.ones((1,), np.int32),
                np.zeros((1, max_w), np.int32), k_pool, v_pool)
            k_pool, v_pool = outs[1], outs[2]
            n += 1
        else:
            for lb in self._prompt_ladder:
                toks = np.zeros((1, lb), np.int32)
                outs = self._prefill_exec(toks, np.ones((1,), np.int32),
                                          np.zeros((1, max_w), np.int32),
                                          k_pool, v_pool)
                k_pool, v_pool = outs[1], outs[2]
                n += 1
        if self.spec_k > 0:
            # spec engines decode through ONE verify + ONE draft signature
            dk, dv = self._draft_pools()
            outs = self._verify_exec(
                np.zeros((self.max_slots, self.spec_k + 1), np.int32),
                np.zeros((self.max_slots,), np.int32),
                np.zeros((self.max_slots,), np.int32),
                np.zeros((self.max_slots, max_w), np.int32),
                k_pool, v_pool)
            k_pool, v_pool = outs[1], outs[2]
            outs = self._draft_exec(
                np.zeros((self.max_slots,), np.int32),
                np.zeros((self.max_slots,), np.int32),
                np.zeros((self.max_slots, max_w), np.int32), dk, dv)
            self._draft_chunk_exec(
                np.zeros((1, self.prefill_chunk), np.int32),
                np.zeros((1,), np.int32), np.ones((1,), np.int32),
                np.zeros((1, max_w), np.int32), outs[1], outs[2])
            n += 3
        elif self.prefill_only:
            # a prefill-only tier never dispatches a decode step: warming
            # the width ladder would only stretch startup
            pass
        else:
            for w in self._width_ladder:
                outs = self._decode_exec(
                    np.zeros((self.max_slots,), np.int32),
                    np.zeros((self.max_slots,), np.int32),
                    np.zeros((self.max_slots, w), np.int32),
                    k_pool, v_pool)
                k_pool, v_pool = outs[1], outs[2]
                n += 1
        after = self.cache_stats()
        self.warmup_report = {
            "signatures": n,
            "compiles": after["misses"] - before,
            "cache": {"hits": after["hits"], "misses": after["misses"]},
        }
        return self.warmup_report

    # -- admission (client threads) --------------------------------------
    def submit(self, prompt, max_new_tokens=None, timeout_ms=None,
               on_token=None, owner=None, temperature=0.0, top_k=0,
               top_p=1.0, seed=None):
        """Submit one generation request; always returns a DecodeStream.

        Rejections come back already terminal (OVERLOADED when the queue
        or the KV block pool cannot take the stream, INVALID_INPUT for a
        prompt outside the menu or sampling options out of range,
        UNAVAILABLE when the breaker is open or the engine is stopped or
        draining) — callers branch on ``status``, never on exceptions,
        exactly like ModelServer.predict.

        ``temperature``/``top_k``/``top_p``/``seed`` select seeded
        host-side sampling (sampling.py); the defaults are greedy and
        bit-identical to the pre-sampling engine.  An explicit ``seed``
        makes the stream replay the same tokens on any engine with the
        same params — the chaos harness and the sequential oracle lean on
        that.

        ``owner`` is the router's fencing token: it is installed on the
        stream before admission and presented on every emission/terminal
        this engine produces, so a handoff (which re-owns the stream) can
        fence this engine out mid-flight."""
        if max_new_tokens is None:
            max_new_tokens = self.max_new_tokens
        deadline = (time.monotonic() + timeout_ms / 1e3
                    if timeout_ms is not None else None)
        try:
            sampling = SamplingParams(temperature, top_k, top_p, seed)
        except ValueError as exc:
            stream = DecodeStream(None, max_new_tokens, deadline,
                                  stats=self.stats, on_token=on_token)
            self.stats.on_invalid()
            stream.complete(INVALID_INPUT, error=str(exc))
            return stream
        if sampling.greedy and sampling.seed is None:
            sampling = None
        elif sampling.seed is None:
            # resolve on the CALLER's thread: the framework key state is
            # thread-local, so deriving here keeps the stream reproducible
            # under the caller's mx.random.seed (the worker thread's state
            # is unrelated)
            from .sampling import resolve_seed
            sampling.seed = resolve_seed(sampling)
        try:
            prompt = self._coerce_prompt(prompt)
        except (TypeError, ValueError) as exc:
            stream = DecodeStream(None, max_new_tokens, deadline,
                                  stats=self.stats, on_token=on_token)
            self.stats.on_invalid()
            stream.complete(INVALID_INPUT, error=str(exc))
            return stream
        stream = DecodeStream(prompt, int(max_new_tokens), deadline,
                              stats=self.stats, on_token=on_token,
                              sampling=sampling)
        if owner is not None:
            stream.set_owner(owner)
        with self._cond:
            closed = self._closed
            draining = self._draining
        if closed or draining:
            self.stats.on_unavailable_rejected()
            stream.complete(UNAVAILABLE,
                            error=("engine draining" if draining
                                   else "engine stopped"))
            return stream
        problem = self._validate(prompt, int(max_new_tokens))
        if problem is not None:
            self.stats.on_invalid()
            stream.complete(INVALID_INPUT, error=problem)
            return stream
        # breaker admission after validation (a request that can never
        # execute must not consume the half-open probe slot)
        decision = self.breaker.admit()
        if decision == REJECT:
            self.stats.on_unavailable_rejected()
            snap = self.breaker.snapshot()
            stream.complete(
                UNAVAILABLE,
                error="circuit open after %d consecutive failure(s); "
                      "retry in <= %.0f ms" % (snap["consecutive_failures"],
                                               snap["backoff_s"] * 1e3))
            return stream
        # KV admission: a stream's worst-case block count is reserved at
        # JOIN time (so an admitted-to-a-slot sequence can always grow to
        # completion — no mid-stream OOM, no eviction); admission itself
        # sheds fast when the pool is exhausted (nothing free and
        # unpromised: queueing more work could not make progress sooner)
        stream.seq_id = next(self._seq_counter)
        if self._cache.available_unreserved() <= 0:
            admitted = "no-blocks"
        else:
            with self._cond:
                if not self._running or self._draining:
                    admitted = "stopping"
                elif len(self._queue) >= self._max_queue:
                    admitted = "full"
                else:
                    self._queue.append(_QEntry(stream, gen=owner))
                    self._cond.notify_all()
                    admitted = True
        if admitted is not True:
            if decision == PROBE:
                self.breaker.release_probe()
            if admitted == "stopping":
                self.stats.on_unavailable_rejected()
                stream.complete(UNAVAILABLE, error="engine shutting down")
            else:
                self.stats.on_shed()
                stream.complete(
                    OVERLOADED,
                    error=("admission queue full" if admitted == "full"
                           else "no free KV blocks"))
            return stream
        stream.admitted = True
        self.stats.on_admitted()
        return stream

    def generate(self, prompt, max_new_tokens=None, timeout_ms=None):
        """Blocking convenience: submit + wait; returns the stream."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           timeout_ms=timeout_ms).result()

    def _validate(self, prompt, max_new_tokens):
        if not 1 <= len(prompt) <= self.max_prompt_len:
            return ("prompt length %d outside [1, %d]"
                    % (len(prompt), self.max_prompt_len))
        if not 1 <= max_new_tokens <= self.max_new_tokens:
            return ("max_new_tokens %d outside [1, %d]"
                    % (max_new_tokens, self.max_new_tokens))
        if prompt.min() < 0 or prompt.max() >= self.model.vocab_size:
            return ("prompt token ids outside [0, %d)"
                    % self.model.vocab_size)
        need = self._blocks_needed(len(prompt), max_new_tokens)
        if need > self._cache.capacity():
            # could NEVER join: reject now instead of starving in the queue
            return ("stream needs %d KV blocks but the pool only has %d"
                    % (need, self._cache.capacity()))
        return None

    def _blocks_needed(self, prompt_len, max_new_tokens):
        """Worst-case block reservation for one stream.  A prefill-only
        engine writes exactly the prompt's pages — the stream leaves at
        its first token, so no decode growth is ever provisioned here."""
        if self.prefill_only:
            return self._cache.blocks_for_tokens(int(prompt_len))
        return self._cache.blocks_for_tokens(int(prompt_len)
                                             + int(max_new_tokens))

    @staticmethod
    def _coerce_prompt(prompt):
        arr = np.asarray(prompt)
        if arr.ndim != 1 or arr.size == 0:
            raise ValueError("prompt must be a non-empty 1-D token id "
                             "sequence, got shape %s" % (arr.shape,))
        if not np.issubdtype(arr.dtype, np.integer):
            if not np.all(arr == np.floor(arr)):
                raise ValueError("prompt token ids must be integers")
        return arr.astype(np.int32)

    # -- scheduler loop (worker thread) ----------------------------------
    def _run(self):
        try:
            self._run_loop()
        except BaseException as exc:
            # the scheduler must never die silently: an exception escaping
            # the narrow per-execution guards (a failed device fetch, a
            # SimulatedCrash BaseException from a fault plan) would leave
            # _running True and every waiter blocked forever, violating
            # the "terminal state is a status, never a hang" contract.
            # Close the engine, drain everything with the retryable
            # status, then re-raise so the death stays observable — UNLESS
            # stop() already closed and drained us: a worker tripping over
            # its own freed KV state after a timed-out shutdown join is
            # routine teardown, not news worth a thread traceback.
            with self._cond:
                already_closed = self._closed
                self._closed = True
                self._running = False
            self._drain(error="decode worker died: %r" % (exc,))
            if not already_closed:
                raise

    def _run_loop(self):  # mxflow: hot (decode prefill/step loop)
        k_pool, v_pool = self._init_pools()
        if self.spec_k > 0 and self._dpools is None:
            self._dpools = self._draft_pools()
        while True:
            with self._cond:
                # idle only when queue AND slots are empty — nothing whose
                # deadline could expire — and submit()/stop() both notify,
                # so the timeout is pure liveness insurance, kept long to
                # avoid burning 20 wakeups/s per idle engine.  A drain
                # parks here too, at a step boundary: the worker publishes
                # its pool handles and signals quiesced so export_stream
                # can read a frozen device state; resume() un-parks it and
                # it continues with the same locals (device content is
                # untouched while parked).
                while self._running and (
                        self._draining
                        or (not self._queue and not any(self._slots))):
                    if self._draining and not self._quiesced.is_set():
                        self._pools = (k_pool, v_pool)
                        self._quiesced.set()
                        self._cond.notify_all()
                    self._cond.wait(0.5)
                if not self._running:
                    return
            self._expire()
            for seq in self._claim_joiners():
                if seq.snap is not None:
                    k_pool, v_pool = self._resume_imported(seq, k_pool,
                                                           v_pool)
                elif self.prefill_chunk is None:
                    k_pool, v_pool = self._prefill(seq.stream, k_pool,
                                                   v_pool)
                # chunked joiners advance below, one chunk per iteration
            if self.prefill_chunk is not None:
                k_pool, v_pool = self._advance_prefill(k_pool, v_pool)
            with self._cond:
                has_live = any(self._slots)
            if has_live:
                if self.spec_k > 0:
                    k_pool, v_pool = self._spec_step(k_pool, v_pool)
                else:
                    k_pool, v_pool = self._step(k_pool, v_pool)

    def _expire(self):
        """TIMEOUT queued and live streams whose deadline passed."""
        now = time.monotonic()
        with self._cond:
            expired_q = [e for e in self._queue if e.stream.expired(now)]
            if expired_q:
                self._queue = deque(e for e in self._queue
                                    if not e.stream.expired(now))
            expired_live = [(i, seq) for i, seq in enumerate(self._slots)
                            if seq is not None
                            and seq.stream.expired(now)]
            for i, _ in expired_live:
                self._slots[i] = None
        # a lost completion means an external fence already terminated
        # the stream; we still held it, so its bucket settles here with
        # the fence's status (see _vacate)
        for e in expired_q:
            self._cache.release(e.stream.seq_id)
            if e.stream.complete(TIMEOUT, error="deadline before prefill",
                                 owner=e.gen):
                self.stats.on_result(TIMEOUT)
            else:
                self.stats.on_result(e.stream.snapshot()[0])
        for _, seq in expired_live:
            self._cache.free_seq(seq.seq_id)
            if seq.stream.complete(TIMEOUT, error="deadline mid-stream",
                                   owner=seq.gen):
                self.stats.on_result(TIMEOUT)
            else:
                self.stats.on_result(seq.stream.snapshot()[0])

    def _claim_joiners(self):
        """Move queued streams into free slots (iteration-level join).

        A stream joins only when its worst-case KV block count can be
        reserved — a stream in a slot can then ALWAYS grow to completion
        (no mid-stream OOM, no eviction).  Joins are strict FIFO: when the
        head cannot reserve, nothing behind it jumps the line, so a big
        request cannot be starved by a stream of small ones.  ``static``
        scheduling (the bench baseline) only admits into an EMPTY batch
        and then runs it to completion — the run-to-completion discipline
        continuous batching replaces."""
        with self._cond:
            if self.scheduling == "static" and any(self._slots):
                return []       # a static batch runs to completion first
        joined = []
        while True:
            with self._cond:
                free_slot = next((i for i in range(self.max_slots)
                                  if self._slots[i] is None), None)
                if free_slot is None or not self._queue:
                    break
                entry = self._queue[0]
                res = None
                if entry.snap is None:
                    blocks = self._blocks_needed(
                        len(entry.stream.prompt),
                        entry.stream.max_new_tokens)
                    if self.prefix_cache:
                        res = self._cache.reserve(
                            entry.stream.seq_id, blocks,
                            prompt=entry.stream.prompt,
                            align_tokens=self.prefill_chunk)
                    else:
                        res = self._cache.reserve(entry.stream.seq_id,
                                                  blocks)
                    if not res:
                        break   # head waits for finishing sequences' blocks
                # imported entries pre-reserved at import_stream time
                self._queue.popleft()
                seq = _Seq(entry.stream, gen=entry.gen, snap=entry.snap)
                if entry.snap is None and self.prefill_chunk is not None:
                    # chunked prompts join mid-prefill: one chunk per
                    # scheduler iteration, decode steps interleaved
                    seq.prefill_pos = getattr(res, "prefix_tokens", 0)
                if entry.snap is None and entry.stream.sampling is not None:
                    seq.sampler = StreamSampler(entry.stream.sampling)
                self._slots[free_slot] = seq
            if self.prefix_cache and entry.snap is None:
                self.stats.on_prefix(getattr(res, "shared_blocks", 0))
            joined.append(seq)
        return joined

    def _vacate(self, seq, status, error=None):
        """Free the sequence's pages and complete its stream (the slot
        entry was already cleared by the caller under ``_cond``).  The
        completion presents this engine's fencing token: losing means a
        router fence terminated the stream while the seq still lived
        here (a kill racing a handoff).  The stream leaves this engine
        exactly once either way, so a lost completion settles the bucket
        with the fence's status — every removal site counts exactly one
        terminal, which is what keeps ``requests + imported == terminals
        + handed_off`` true per engine."""
        self._cache.free_seq(seq.seq_id)
        if seq.stream.complete(status, error=error, owner=seq.gen):
            self.stats.on_result(status)
        else:
            self.stats.on_result(seq.stream.snapshot()[0])

    def _fail_all(self, exc):
        """A batch execution failed beyond the retry budget: fail every
        live stream (the per-stream view of MicroBatcher's batch ERROR)."""
        with self._cond:
            live = [(i, seq) for i, seq in enumerate(self._slots)
                    if seq is not None]
            for i, _ in live:
                self._slots[i] = None
        for _, seq in live:
            self._vacate(seq, ERROR, error=repr(exc))

    def _prefill(self, stream, k_pool, v_pool):
        """Run one joining request's prompt and emit its first token."""
        seq = None
        with self._cond:
            for cand in self._slots:
                if cand is not None and cand.stream is stream:
                    seq = cand
                    break
        if seq is None:          # vacated between join and prefill
            return k_pool, v_pool
        prompt = stream.prompt
        self._cache.ensure_capacity(seq.seq_id, len(prompt))
        lb = self._prompt_ladder.bucket(len(prompt))
        toks = np.zeros((1, lb), np.int32)
        toks[0, :len(prompt)] = prompt
        table = np.asarray(
            [self._cache.table(seq.seq_id, self._width_ladder.max_batch)],
            np.int32)
        try:
            outs = self._prefill_exec(toks,
                                      np.asarray([len(prompt)], np.int32),
                                      table, k_pool, v_pool)
        except Exception as exc:
            self.breaker.on_failure()
            with self._cond:
                for i, cand in enumerate(self._slots):
                    if cand is seq:
                        self._slots[i] = None
            self._vacate(seq, ERROR, error=repr(exc))
            return k_pool, v_pool
        self.breaker.on_success()
        logits = outs[0].asnumpy()[0]  # mxflow: sync-ok(ttft token fetch: the first sampled token must reach the host to stream it)
        token = self._select_token(seq, logits)
        seq.position = len(prompt)
        seq.cur_token = token
        seq.generated = 1
        stream._emit(token, owner=seq.gen)
        # TTFT from SUBMISSION (queue wait included — the number a client
        # experiences), taken from the stream's own record so snapshot and
        # bench artifact report the same sample, not two timestamps
        _, _, ttft, _, _ = stream.snapshot()
        if ttft is None:        # emit raced a terminal claim
            ttft = (time.monotonic() - stream.t_submit) * 1e3
        self.stats.on_prefill(ttft)
        self.stats.on_tokens(1)
        self._maybe_finish(seq, token)
        self.stats.on_idle(self._live_count(), self._cache.used())
        return outs[1], outs[2]

    def _select_token(self, seq, logits_row):
        """Next token from a host logits row: argmax, or the stream's
        seeded sampler (sampling.py) — host-side either way, so the
        compiled kernels are identical for greedy and sampled streams."""
        if seq.sampler is None:
            return int(np.argmax(logits_row))
        return seq.sampler.sample(logits_row)

    def _cow_pages(self, seq, first_pos, last_pos, k_pool, v_pool):
        """Copy-on-write guard for a write to positions [first, last]:
        fork every shared block covering them (cache swaps the table
        entry; we copy the device pages so the fork starts bit-identical
        to the shared original).  Draft pools fork the same block ids —
        the draft pool is indexed by the target's page table."""
        from ...ndarray import NDArray
        bs = self._cache.block_size
        for idx in range(int(first_pos) // bs, int(last_pos) // bs + 1):
            blk, src = self._cache.writable(seq.seq_id, idx)
            if src is None:
                continue
            k_pool = NDArray(k_pool._data.at[:, blk].set(
                k_pool._data[:, src]))
            v_pool = NDArray(v_pool._data.at[:, blk].set(
                v_pool._data[:, src]))
            if self._dpools is not None:
                dk, dv = self._dpools
                self._dpools = [
                    NDArray(dk._data.at[:, blk].set(dk._data[:, src])),
                    NDArray(dv._data.at[:, blk].set(dv._data[:, src]))]
            self.stats.on_cow_fork()
        return k_pool, v_pool

    def _advance_prefill(self, k_pool, v_pool):
        """Run ONE prompt chunk for the oldest mid-prefill stream.

        One chunk per scheduler iteration is the interleave: a long
        prompt's chunks alternate with decode steps for live streams, so
        their inter-token latency (and queued streams' TTFT) no longer
        spikes behind it.  Every chunk is the same ``[1, C]`` signature —
        prefix-cache hits just start the loop at the first unshared
        chunk."""
        with self._cond:
            pending = [s for s in self._slots
                       if s is not None and s.prefill_pos is not None]
        if not pending:
            return k_pool, v_pool
        seq = min(pending, key=lambda s: s.seq_id)
        stream = seq.stream
        prompt = stream.prompt
        L = len(prompt)
        C = self.prefill_chunk
        s0 = seq.prefill_pos
        n = min(C, L - s0)
        self._cache.ensure_capacity(seq.seq_id, s0 + n)
        if self.prefix_cache:
            k_pool, v_pool = self._cow_pages(seq, s0, s0 + n - 1,
                                             k_pool, v_pool)
        max_w = self._width_ladder.max_batch
        table = np.asarray([self._cache.table(seq.seq_id, max_w)], np.int32)
        toks = np.zeros((1, C), np.int32)
        toks[0, :n] = prompt[s0:s0 + n]
        start = np.asarray([s0], np.int32)
        length = np.asarray([n], np.int32)
        try:
            outs = self._chunk_exec(toks, start, length, table, k_pool,
                                    v_pool)
            if self.spec_k > 0:
                dk, dv = self._dpools
                douts = self._draft_chunk_exec(toks, start, length, table,
                                               dk, dv)
                self._dpools = [douts[1], douts[2]]
        except Exception as exc:
            self.breaker.on_failure()
            with self._cond:
                for i, cand in enumerate(self._slots):
                    if cand is seq:
                        self._slots[i] = None
            self._vacate(seq, ERROR, error=repr(exc))
            return k_pool, v_pool
        self.breaker.on_success()
        k_pool, v_pool = outs[1], outs[2]
        if s0 + n < L:
            seq.prefill_pos = s0 + n
            return k_pool, v_pool
        # final chunk: the prompt's K/V is complete — publish it for
        # cross-request reuse, then emit the TTFT token
        seq.prefill_pos = None
        if self.prefix_cache:
            self._cache.register_prefix(seq.seq_id, prompt)
        logits = outs[0].asnumpy()[0]  # mxflow: sync-ok(ttft token fetch: the first sampled token must reach the host to stream it)
        token = self._select_token(seq, logits)
        seq.position = L
        seq.cur_token = token
        seq.generated = 1
        stream._emit(token, owner=seq.gen)
        _, _, ttft, _, _ = stream.snapshot()
        if ttft is None:        # emit raced a terminal claim
            ttft = (time.monotonic() - stream.t_submit) * 1e3
        self.stats.on_prefill(ttft)
        self.stats.on_tokens(1)
        if self.prefill_only:
            if not self._maybe_finish(seq, token):
                return self._handoff_first_token(seq, k_pool, v_pool)
        else:
            self._maybe_finish(seq, token)
        self.stats.on_idle(self._live_count(), self._cache.used())
        return k_pool, v_pool

    def _handoff_first_token(self, seq, k_pool, v_pool):
        """Prefill-only mode: the stream leaves this engine AT its first
        token.  The sequence's prompt K/V pages, cursor, and sampler
        state are snapshotted (the exact ``export_stream`` dict shape),
        its blocks return to the pool, and the installed handoff sink
        decides where the stream decodes — a truthy return means the
        stream found a decode home and leaves this engine's accounting
        through ``handed_off``; anything else (no sink, a False return,
        an exception) terminates it here with the retryable UNAVAILABLE,
        its one-token prefix intact for re-admission.

        No quiesce is needed: the worker thread owns the pool locals at
        this point, so the pages read out are exactly the state the final
        chunk left behind — the importer's restore is bitwise."""
        stream = seq.stream
        with self._cond:
            for i, cand in enumerate(self._slots):
                if cand is seq:
                    self._slots[i] = None
        status, tokens, _, _, _ = stream.snapshot()
        if status is not None:
            # terminal while prefilling (fenced by the router): counters
            # settled wherever it was completed; just return its blocks
            self._cache.free_seq(seq.seq_id)
            return k_pool, v_pool
        sampling = None
        if stream.sampling is not None:
            sampling = stream.sampling.as_dict()
            if seq.sampler is not None:
                sampling.update(seq.sampler.state())
            else:
                sampling.setdefault("draws", 0)
        need = self._cache.blocks_for_tokens(seq.position)
        blocks = self._cache.blocks_of(seq.seq_id)[:need]
        idx = np.asarray(blocks, np.int32)
        snap = {
            "prompt": np.asarray(stream.prompt, np.int32).copy(),
            "max_new_tokens": int(stream.max_new_tokens),
            "tokens": list(tokens),
            "geometry": {
                "block_size": self._cache.block_size,
                "num_layers": self.model.num_layers,
                "num_heads": self.model.num_heads,
                "head_dim": self.model.head_dim,
                "vocab_size": self.model.vocab_size,
            },
            "position": int(seq.position),
            "cur_token": int(seq.cur_token),
            "generated": int(seq.generated),
            "k": k_pool.asnumpy()[:, idx].copy(),  # mxflow: sync-ok(first-token handoff: prompt K pages leave the prefill tier once per stream)
            "v": v_pool.asnumpy()[:, idx].copy(),  # mxflow: sync-ok(first-token handoff: prompt V pages leave the prefill tier once per stream)
            "sampling": sampling,
        }
        self._cache.free_seq(seq.seq_id)
        cb = self._handoff_cb
        handed = False
        if cb is not None:
            try:
                handed = bool(cb(stream, snap))
            except Exception:
                handed = False
        if handed:
            self.stats.on_handed_off()
        else:
            # the sink may have already fence-terminated the stream (an
            # exhausted adoption search completes it UNAVAILABLE with a
            # private token), so this complete can lose — but the stream
            # leaves this engine either way, and conservation needs
            # exactly one bucket for it here
            stream.complete(UNAVAILABLE,
                            error="prefill tier found no decode home; "
                                  "re-admit with the emitted prefix as "
                                  "prompt",
                            owner=seq.gen)
            self.stats.on_result(UNAVAILABLE)
        self.stats.on_idle(self._live_count(), self._cache.used())
        return k_pool, v_pool

    def set_handoff(self, cb):
        """Install the first-token handoff sink ``cb(stream, snap) ->
        bool`` for a prefill-only engine (serving/disagg/ wires this to
        the decode tier's adoption path).  The sink runs on the worker
        thread between the final prompt chunk and the stream's departure;
        it must not block on this engine."""
        if not self.prefill_only:
            raise MXNetError("set_handoff requires prefill_only=True")
        self._handoff_cb = cb

    def _maybe_finish(self, seq, token):
        """OK-complete a sequence that hit EOS or its token budget."""
        eos = getattr(self.model, "eos_id", None)
        if seq.generated >= seq.stream.max_new_tokens or \
                (eos is not None and token == eos):
            with self._cond:
                for i, cand in enumerate(self._slots):
                    if cand is seq:
                        self._slots[i] = None
            self._vacate(seq, OK)
            return True
        return False

    def _live_count(self):
        with self._cond:
            return sum(1 for s in self._slots if s is not None)

    def _step(self, k_pool, v_pool):
        """One fixed-shape decode iteration over every live slot."""
        with self._cond:
            slots = list(self._slots)
        live = [seq for seq in slots
                if seq is not None and seq.prefill_pos is None]
        if not live:
            return k_pool, v_pool
        # lazily grow page tables to cover this step's write index, then
        # pick the smallest precompiled width covering the longest one
        for seq in live:
            self._cache.ensure_capacity(seq.seq_id, seq.position + 1)
            if self.prefix_cache:
                k_pool, v_pool = self._cow_pages(seq, seq.position,
                                                 seq.position, k_pool,
                                                 v_pool)
        max_tokens = max(seq.position + 1 for seq in live)
        width = self._width_ladder.bucket(
            self._cache.blocks_for_tokens(max_tokens))
        tokens = np.zeros((self.max_slots,), np.int32)
        positions = np.zeros((self.max_slots,), np.int32)
        tables = np.zeros((self.max_slots, width), np.int32)
        for i, seq in enumerate(slots):
            if seq is None or seq.prefill_pos is not None:
                continue
            tokens[i] = seq.cur_token
            positions[i] = seq.position
            tables[i] = self._cache.table(seq.seq_id, width)
        t0 = time.monotonic()
        try:
            outs = self._decode_exec(tokens, positions, tables, k_pool,
                                     v_pool)
        except Exception as exc:
            self.breaker.on_failure()
            self._fail_all(exc)
            return k_pool, v_pool
        self.breaker.on_success()
        logits = outs[0].asnumpy()  # mxflow: sync-ok(per-step token fetch: sampled ids must reach the host to stream)
        emitted = 0
        for i, seq in enumerate(slots):
            if seq is None or seq.prefill_pos is not None:
                continue
            with self._cond:
                if self._slots[i] is not seq:
                    continue     # vacated mid-step (teardown race)
            token = self._select_token(seq, logits[i])
            seq.position += 1
            seq.cur_token = token
            seq.generated += 1
            seq.stream._emit(token, owner=seq.gen)
            emitted += 1
            self._maybe_finish(seq, token)
        self.stats.on_step(len(live), emitted,
                           (time.monotonic() - t0) * 1e3,
                           self._cache.used())
        return outs[1], outs[2]

    def _spec_step(self, k_pool, v_pool):  # mxflow: hot (speculative verify loop)
        """One speculative round: draft proposes K tokens in one unrolled
        call, ONE paged verify call scores all K+1 positions, and every
        live slot commits the longest prefix where the draft agrees with
        the target — up to K+1 tokens for two dispatches.

        Emitted tokens come exclusively from the target's logits rows
        (row i is the target's distribution after the first i+1 round
        tokens), so the committed sequence is the target's greedy chain
        no matter what the draft proposed: wrong, stale, or cold draft
        state only lowers the acceptance rate.  Sampled slots use one
        valid row and draw from row 0 — one seeded host draw per token,
        same replay contract as the non-speculative path."""
        with self._cond:
            slots = list(self._slots)
        live = [seq for seq in slots
                if seq is not None and seq.prefill_pos is None]
        if not live:
            return k_pool, v_pool
        K1 = self.spec_k + 1
        width = self._width_ladder.max_batch
        valid_by = {}
        for seq in live:
            rem = seq.stream.max_new_tokens - seq.generated
            v = 1 if seq.sampler is not None else max(1, min(K1, rem))
            valid_by[id(seq)] = v
            # verify writes K/V for every valid row; rows past the budget
            # are invalid (trash block), so capacity never exceeds the
            # admission reservation
            self._cache.ensure_capacity(seq.seq_id, seq.position + v)
            if self.prefix_cache:
                k_pool, v_pool = self._cow_pages(
                    seq, seq.position, seq.position + v - 1, k_pool, v_pool)
        tokens = np.zeros((self.max_slots, K1), np.int32)
        positions = np.zeros((self.max_slots,), np.int32)
        valids = np.zeros((self.max_slots,), np.int32)
        tables = np.zeros((self.max_slots, width), np.int32)
        cur = np.zeros((self.max_slots,), np.int32)
        for i, seq in enumerate(slots):
            if seq is None or seq.prefill_pos is not None:
                continue
            positions[i] = seq.position
            valids[i] = valid_by[id(seq)]
            tables[i] = self._cache.table(seq.seq_id, width)
            cur[i] = seq.cur_token
        t0 = time.monotonic()
        try:
            dk, dv = self._dpools
            douts = self._draft_exec(cur, positions, tables, dk, dv)
            self._dpools = [douts[1], douts[2]]
            props = douts[0].asnumpy()  # mxflow: sync-ok(draft proposals feed the verify call's token rows)
            tokens[:, 0] = cur
            tokens[:, 1:] = props
            outs = self._verify_exec(tokens, positions, valids, tables,
                                     k_pool, v_pool)
        except Exception as exc:
            self.breaker.on_failure()
            self._fail_all(exc)
            return k_pool, v_pool
        self.breaker.on_success()
        logits = outs[0].asnumpy()  # mxflow: sync-ok(per-round token fetch: accepted ids must reach the host to stream)
        emitted_total = 0
        eos = getattr(self.model, "eos_id", None)
        for i, seq in enumerate(slots):
            if seq is None or seq.prefill_pos is not None:
                continue
            with self._cond:
                if self._slots[i] is not seq:
                    continue     # vacated mid-round (teardown race)
            v = int(valids[i])
            rows = logits[i]
            emitted = []
            j = 0
            while True:
                tok = self._select_token(seq, rows[j])
                emitted.append(tok)
                if eos is not None and tok == eos:
                    break
                if j >= v - 1:
                    break        # last valid row consumed
                if int(tokens[i, j + 1]) != tok:
                    break        # draft diverged: later rows scored the
                                 # wrong token chain
                j += 1
            if seq.sampler is None and v > 1:
                self.stats.on_spec(v - 1, len(emitted) - 1)
            for tok in emitted:
                seq.position += 1
                seq.generated += 1
                seq.cur_token = tok
                seq.stream._emit(tok, owner=seq.gen)
            emitted_total += len(emitted)
            self._maybe_finish(seq, emitted[-1])
        self.stats.on_step(len(live), emitted_total,
                           (time.monotonic() - t0) * 1e3,
                           self._cache.used())
        return outs[1], outs[2]

    def _resume_imported(self, seq, k_pool, v_pool):
        """Continue an imported stream: scatter its snapshot's K/V pages
        into this engine's pools at the blocks just granted to it, restore
        the (position, cur_token, generated) cursor, and let the normal
        decode step take it from there.  The restore is bitwise: float32
        pages round-trip host<->device exactly, and the decode math for a
        slot depends only on (params, cur_token, position, K/V pages
        0..position-1), so the continued stream equals the uninterrupted
        reference token for token."""
        from ...ndarray import NDArray
        snap = seq.snap
        seq.snap = None
        samp = snap.get("sampling")
        if samp is not None:
            params = SamplingParams(samp["temperature"], samp["top_k"],
                                    samp["top_p"], samp["seed"])
            seq.stream.sampling = params
            seq.sampler = StreamSampler.restore(params, samp["seed"],
                                                samp.get("draws", 0))
        if snap["generated"] == 0 or snap.get("k") is None:
            # exported before its prefill ran: nothing to restore — run
            # the normal prompt path on this engine
            if self.prefill_chunk is not None:
                seq.prefill_pos = 0
                return k_pool, v_pool
            return self._prefill(seq.stream, k_pool, v_pool)
        position = int(snap["position"])
        self._cache.ensure_capacity(seq.seq_id, position)
        blocks = self._cache.blocks_of(seq.seq_id)
        idx = np.asarray(blocks, np.int32)
        # the snapshot's K/V pages stage host->device as two transient
        # buffers, consumed by the scatter below; the paired free keeps
        # the region balanced while its peak records the staging cost
        from ... import memory_accounting
        staged = int(snap["k"].nbytes) + int(snap["v"].nbytes)
        region = "%s:import" % self._cache.account_region
        memory_accounting.record_alloc(staged, region, count=2)
        k_pool = NDArray(k_pool._data.at[:, idx].set(snap["k"]))
        v_pool = NDArray(v_pool._data.at[:, idx].set(snap["v"]))
        memory_accounting.record_free(staged, region, count=2)
        seq.position = position
        seq.cur_token = int(snap["cur_token"])
        seq.generated = int(snap["generated"])
        self.stats.on_idle(self._live_count(), self._cache.used())
        return k_pool, v_pool

    # -- drain / handoff (router threads) ---------------------------------
    def quiesce(self, timeout_s=5.0):
        """Stop admitting and park the scheduler at a step boundary.

        Returns True once the worker is parked with its pool handles
        published (export_stream is only legal then: the device pools are
        frozen, no step is mutating pages).  False on timeout — the
        caller treats the engine as wedged and fences its streams instead
        of exporting them.  Idempotent; ``resume()`` reverses it."""
        with self._cond:
            if self._closed:
                return False
            self._draining = True
            parked = self._quiesced
            self._cond.notify_all()
        # wait OFF-lock: the worker needs _cond to park and set the event
        return parked.wait(timeout_s)

    def resume(self):
        """Reopen admission and un-park the scheduler (a drain that was
        cancelled, or a drained replica re-enabled)."""
        with self._cond:
            self._draining = False
            self._pools = None
            self._quiesced.clear()
            self._cond.notify_all()

    def export_streams(self):
        """Snapshot-and-remove every non-terminal queued/live stream (the
        drain sweep); returns ``[(stream, snapshot), ...]``.  Requires a
        successful ``quiesce()``."""
        with self._cond:
            targets = [e.stream for e in self._queue] \
                + [seq.stream for seq in self._slots if seq is not None]
        out = []
        for stream in targets:
            snap = self.export_stream(stream)
            if snap is not None:
                out.append((stream, snap))
        return out

    def export_stream(self, stream):
        """Extract one stream's resumable state and release its resources
        here: emitted-token prefix, generation cursor, and an exact host
        copy of its valid K/V pages (positions ``0..position-1``).  The
        stream leaves this engine's accounting through ``handed_off`` —
        it will terminate wherever ``import_stream`` lands it.  Returns
        None when the stream is unknown here or already terminal."""
        with self._cond:
            if not self._quiesced.is_set():
                raise MXNetError("export_stream requires a quiesced "
                                 "engine: call quiesce() first")
            entry = next((e for e in self._queue if e.stream is stream),
                         None)
            seq = None
            if entry is not None:
                self._queue.remove(entry)
            else:
                for i, cand in enumerate(self._slots):
                    if cand is not None and cand.stream is stream:
                        seq = cand
                        self._slots[i] = None
                        break
            pools = self._pools
        if entry is None and seq is None:
            return None
        status, tokens, _, _, _ = stream.snapshot()
        if status is not None:
            # terminal while still held: the engine's own terminations
            # always remove the stream before completing, so a terminal
            # found here means an external fence won — settle the bucket
            # (see _vacate) and return its blocks (free_seq also drops
            # any outstanding reservation)
            self._cache.free_seq(stream.seq_id)
            self.stats.on_result(status)
            return None
        geometry = {
            "block_size": self._cache.block_size,
            "num_layers": self.model.num_layers,
            "num_heads": self.model.num_heads,
            "head_dim": self.model.head_dim,
            "vocab_size": self.model.vocab_size,
        }
        sampling = None
        if stream.sampling is not None:
            sampling = stream.sampling.as_dict()
            if seq is not None and seq.sampler is not None:
                # effective seed + draws so far: the importer rebuilds the
                # RandomState and burns the draws, continuing the exact
                # uniform sequence this stream would have used here
                sampling.update(seq.sampler.state())
            else:
                sampling.setdefault("draws", 0)
        if seq is not None and seq.snap is not None:
            # imported here but never resumed: re-export the snapshot
            snap = dict(seq.snap)
        elif entry is not None and entry.snap is not None:
            snap = dict(entry.snap)
        elif seq is not None and seq.generated > 0:
            need = self._cache.blocks_for_tokens(seq.position)
            blocks = self._cache.blocks_of(seq.seq_id)[:need]
            idx = np.asarray(blocks, np.int32)
            k_pool, v_pool = pools
            snap = {
                "prompt": np.asarray(stream.prompt, np.int32).copy(),
                "max_new_tokens": int(stream.max_new_tokens),
                "tokens": list(tokens),
                "geometry": geometry,
                "position": int(seq.position),
                "cur_token": int(seq.cur_token),
                "generated": int(seq.generated),
                "k": k_pool.asnumpy()[:, idx].copy(),  # mxflow: sync-ok(quiesced drain: K pages leave the device once per handoff)
                "v": v_pool.asnumpy()[:, idx].copy(),  # mxflow: sync-ok(quiesced drain: V pages leave the device once per handoff)
                "sampling": sampling,
                "generation": self.generation,
            }
        else:
            # still queued (or joined but not yet prefilled): no device
            # state exists — the importer reruns the prompt from scratch
            snap = {
                "prompt": np.asarray(stream.prompt, np.int32).copy(),
                "max_new_tokens": int(stream.max_new_tokens),
                "tokens": list(tokens),
                "geometry": geometry,
                "position": 0,
                "cur_token": 0,
                "generated": 0,
                "k": None,
                "v": None,
                "sampling": sampling,
                "generation": self.generation,
            }
        self._cache.free_seq(stream.seq_id)
        self.stats.on_handed_off()
        self.stats.on_idle(self._live_count(), self._cache.used())
        return snap

    def import_stream(self, snap, stream=None, owner=None):
        """Admit a snapshot exported elsewhere; the stream resumes at the
        head of the queue with its worst-case KV blocks reserved up
        front.  ``stream`` is the original client handle (its token
        prefix continues seamlessly); without one, a fresh pre-seeded
        stream is built.  ``owner`` is installed as the fencing token
        BEFORE this call by the router (via ``stream.set_owner``) — the
        token presented here must match it, or the import is refused
        (the stale-zombie guard).  Raises :class:`MXNetError` on
        geometry mismatch, no KV headroom, or a closed/draining engine —
        the router's cue to try another survivor."""
        geometry = snap["geometry"]
        mine = {
            "block_size": self._cache.block_size,
            "num_layers": self.model.num_layers,
            "num_heads": self.model.num_heads,
            "head_dim": self.model.head_dim,
            "vocab_size": self.model.vocab_size,
        }
        if geometry != mine:
            raise MXNetError("snapshot geometry %r does not match engine "
                             "%r geometry %r" % (geometry, self.name, mine))
        if snap.get("generation") != self.generation:
            # the half-loaded-model guard: K/V pages written by one weight
            # generation must never be read by another's attention — a
            # stream finishes on the generation it started on (invariant 13)
            raise MXNetError(
                "snapshot from weight generation %r cannot resume on "
                "engine %r serving generation %r"
                % (snap.get("generation"), self.name, self.generation))
        if self.prefill_only and int(snap["generated"]) > 0:
            # mid-decode state needs decode steps this tier never runs;
            # only not-yet-prefilled streams may migrate within the tier
            raise MXNetError("prefill-only engine %r cannot resume a "
                             "stream that already decoded %d token(s)"
                             % (self.name, int(snap["generated"])))
        prompt = np.asarray(snap["prompt"], np.int32)
        if stream is None:
            sampling = None
            samp = snap.get("sampling")
            if samp is not None:
                sampling = SamplingParams(samp["temperature"],
                                          samp["top_k"], samp["top_p"],
                                          samp["seed"])
            stream = DecodeStream(prompt, int(snap["max_new_tokens"]),
                                  stats=self.stats, sampling=sampling)
            if owner is not None:
                stream.set_owner(owner)
            with stream._cond:
                stream._tokens.extend(int(t) for t in snap["tokens"])
        elif stream.owner() != owner:
            raise MXNetError("import_stream fencing token %r does not own "
                             "the stream (owner %r)" % (owner,
                                                        stream.owner()))
        stream.stats = self.stats
        need = self._blocks_needed(len(prompt),
                                   int(snap["max_new_tokens"]))
        with self._cond:
            if self._closed or self._draining or not self._running:
                raise MXNetError("engine %r is not accepting streams"
                                 % self.name)
        seq_id = next(self._seq_counter)
        stream.seq_id = seq_id
        if not self._cache.reserve(seq_id, need):
            raise MXNetError("engine %r has no KV headroom for %d blocks"
                             % (self.name, need))
        with self._cond:
            if self._closed or self._draining or not self._running:
                # lost a teardown race after reserving: give it back
                self._cache.release(seq_id)
                raise MXNetError("engine %r is not accepting streams"
                                 % self.name)
            self._queue.appendleft(_QEntry(stream, gen=owner, snap=snap))
            self._cond.notify_all()
        self.stats.on_imported()
        return stream

    def routing_signals(self):
        """The live signals the fleet's placement score consumes — cheap,
        lock-consistent reads, no XLA."""
        with self._cond:
            queue_depth = len(self._queue)
            slots_live = sum(1 for s in self._slots if s is not None)
            draining = self._draining or self._closed
        snap = self.stats.snapshot()
        kv = self._cache.stats()
        from ... import memory_accounting
        mem = memory_accounting.memory_counters().get(
            self._cache.account_region, {})
        free_blocks = self._cache.available_unreserved()
        return {
            # available_unreserved counts a page shared by N sequences
            # ONCE — the fleet's headroom math sees real free blocks, not
            # N-times-counted shared ones
            "kv_blocks_free": free_blocks,
            "kv_capacity": self._cache.capacity(),
            "kv_block_size": self._cache.block_size,
            # bytes-based headroom from the HBM accountant + block geometry
            # (memory_accounting.py): what scaling_advice() aggregates
            "kv_block_bytes": kv["block_bytes"],
            "kv_bytes_free": free_blocks * kv["block_bytes"],
            "kv_bytes_capacity": self._cache.capacity() * kv["block_bytes"],
            "kv_bytes_live": int(mem.get("live_bytes", 0)),
            "kv_bytes_peak": int(mem.get("peak_bytes", 0)),
            "queue_depth": queue_depth,
            "max_queue": self._max_queue,
            "slots_live": slots_live,
            "max_slots": self.max_slots,
            "tokens_per_s": snap["tokens_per_s"],
            "tp_degree": self.tp_degree,
            "draining": draining,
            "generation": self.generation,
            "prefix_hits": kv["prefix_hits"],
            "prefix_blocks_shared": kv["prefix_blocks_shared"],
            "cow_forks": kv["cow_forks"],
        }

    # -- reference path ---------------------------------------------------
    def generate_reference(self, prompt, max_new_tokens=None,
                           temperature=0.0, top_k=0, top_p=1.0, seed=None):
        """Decode ``prompt`` one-request-at-a-time, bypassing the
        scheduler: fresh private pools, the same CachedOp signatures the
        live engine dispatches (batch ``[max_slots]`` with one live slot).
        This is the bitwise reference the acceptance gate compares
        continuous-batched outputs against, so it mirrors the engine's
        configured kernel path exactly: chunked engines prefill through
        the same ``[1, C]`` chunk signature, speculative engines decode
        through the same ``[S, K+1]`` verify signature with ONE valid row
        per call (sequential — no draft, no speculation; speculation only
        changes how many of these rows commit per dispatch, never their
        logits).  Sampling options replay a sampled stream: an explicit
        ``seed`` makes the output a pure function of the arguments."""
        if max_new_tokens is None:
            max_new_tokens = self.max_new_tokens
        prompt = self._coerce_prompt(prompt)
        problem = self._validate(prompt, int(max_new_tokens))
        if problem is not None:
            raise MXNetError(problem)
        sampler = None
        params = SamplingParams(temperature, top_k, top_p, seed)
        if not (params.greedy and params.seed is None):
            sampler = StreamSampler(params)

        def pick(row):
            if sampler is None:
                return int(np.argmax(row))
            return sampler.sample(row)

        k_pool, v_pool = self._init_pools()
        blocks = list(range(1, 1 + self._cache.blocks_for_tokens(
            len(prompt) + int(max_new_tokens))))
        have = self._cache.blocks_for_tokens(len(prompt))
        max_w = self._width_ladder.max_batch
        if self.prefill_chunk is not None:
            C = self.prefill_chunk
            table = np.zeros((1, max_w), np.int32)
            table[0, :have] = blocks[:have]
            outs = None
            for s0 in range(0, len(prompt), C):
                n = min(C, len(prompt) - s0)
                toks = np.zeros((1, C), np.int32)
                toks[0, :n] = prompt[s0:s0 + n]
                outs = self._chunk_exec(toks, np.asarray([s0], np.int32),
                                        np.asarray([n], np.int32), table,
                                        k_pool, v_pool)
                k_pool, v_pool = outs[1], outs[2]
        else:
            lb = self._prompt_ladder.bucket(len(prompt))
            toks = np.zeros((1, lb), np.int32)
            toks[0, :len(prompt)] = prompt
            table = np.zeros((1, max_w), np.int32)
            table[0, :have] = blocks[:have]
            outs = self._prefill_exec(toks,
                                      np.asarray([len(prompt)], np.int32),
                                      table, k_pool, v_pool)
            k_pool, v_pool = outs[1], outs[2]
        token = pick(outs[0].asnumpy()[0])  # mxflow: sync-ok(reference path: single-stream oracle, correctness over speed)
        out_tokens = [token]
        position = len(prompt)
        eos = getattr(self.model, "eos_id", None)
        while len(out_tokens) < int(max_new_tokens) and token != eos:
            need = self._cache.blocks_for_tokens(position + 1)
            have = max(have, need)
            if self.spec_k > 0:
                K1 = self.spec_k + 1
                tokens = np.zeros((self.max_slots, K1), np.int32)
                positions = np.zeros((self.max_slots,), np.int32)
                valids = np.zeros((self.max_slots,), np.int32)
                tables = np.zeros((self.max_slots, max_w), np.int32)
                tokens[0, 0] = token
                positions[0] = position
                valids[0] = 1
                tables[0, :have] = blocks[:have]
                outs = self._verify_exec(tokens, positions, valids, tables,
                                         k_pool, v_pool)
                row = outs[0].asnumpy()[0, 0]  # mxflow: sync-ok(reference path: single-stream oracle, correctness over speed)
            else:
                width = self._width_ladder.bucket(need)
                tokens = np.zeros((self.max_slots,), np.int32)
                positions = np.zeros((self.max_slots,), np.int32)
                tables = np.zeros((self.max_slots, width), np.int32)
                tokens[0] = token
                positions[0] = position
                tables[0, :have] = blocks[:have]
                outs = self._decode_exec(tokens, positions, tables, k_pool,
                                         v_pool)
                row = outs[0].asnumpy()[0]  # mxflow: sync-ok(reference path: single-stream oracle, correctness over speed)
            k_pool, v_pool = outs[1], outs[2]
            token = pick(row)
            out_tokens.append(token)
            position += 1
        return np.asarray(out_tokens, np.int32)

    # -- observability ----------------------------------------------------
    def cache_stats(self):
        """Merged per-signature compile-cache counters of the prefill and
        decode CachedOps (``prefill|``/``decode|`` key prefixes)."""
        merged = {}
        hits = misses = 0
        pairs = [("prefill", self._prefill_cop),
                 ("decode", self._decode_cop)]
        if self.prefill_chunk is not None:
            pairs.append(("chunk", self._chunk_cop))
        if self.spec_k > 0:
            pairs.extend([("verify", self._verify_cop),
                          ("draft", self._draft_cop),
                          ("draft_chunk", self._draft_chunk_cop)])
        for prefix, cop in pairs:
            st = cop.cache_stats()
            for sig, rec in st["signatures"].items():
                merged["%s|%s" % (prefix, sig)] = dict(rec)
            hits += st["hits"]
            misses += st["misses"]
        return {"signatures": merged, "hits": hits, "misses": misses,
                "recompiles": misses}

    def kv_stats(self):
        return self._cache.stats()

    def health(self):
        return self.breaker.health()

    def stats_snapshot(self):
        """Full engine snapshot (the ``ModelServer.stats()`` analog)."""
        snap = self.stats.snapshot()
        cache = self.cache_stats()
        snap["cache"] = {"hits": cache["hits"], "misses": cache["misses"],
                         "recompiles": cache["recompiles"],
                         "signatures": len(cache["signatures"])}
        snap["warmup"] = self.warmup_report
        snap["kv"] = self.kv_stats()
        # live pool headroom (not the step-sampled counter): capacity and
        # blocks neither allocated nor promised — the routing signal
        snap["kv_capacity"] = self._cache.capacity()
        snap["kv_blocks_free"] = self._cache.available_unreserved()
        snap["health"] = self.breaker.health()
        snap["breaker"] = self.breaker.snapshot()
        with self._cond:
            snap["queue_depth"] = len(self._queue)
            snap["slots_live"] = sum(1 for s in self._slots if s is not None)
            snap["draining"] = self._draining
        snap["scheduling"] = self.scheduling
        snap["generation"] = self.generation
        return snap

    # -- lifecycle ---------------------------------------------------------
    def stop(self):
        """Tear down; every queued or live stream terminates with the
        retryable UNAVAILABLE status and every KV block returns to the
        pool — no waiter left hanging, allocated == freed after drain."""
        with self._cond:
            self._closed = True
            self._running = False
            self._cond.notify_all()
        self._thread.join(timeout=5)
        self._drain(error="engine shutting down")

    def _drain(self, error):
        """Terminate every queued and live stream with UNAVAILABLE and
        return their KV blocks; idempotent (first completion wins,
        freeing an already-freed sequence is a no-op)."""
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
            live = [seq for seq in self._slots if seq is not None]
            self._slots = [None] * self.max_slots
        for e in leftovers:
            self._cache.release(e.stream.seq_id)
            if e.stream.complete(UNAVAILABLE, error=error, owner=e.gen):
                self.stats.on_result(UNAVAILABLE)
            else:
                # externally fenced while queued: settle the bucket here
                # (see _vacate)
                self.stats.on_result(e.stream.snapshot()[0])
        for seq in live:
            self._vacate(seq, UNAVAILABLE, error=error)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
