"""Paged KV cache: a device-resident block pool + per-sequence page tables.

The whole point of continuous batching collapses if KV memory is laid out
``[max_slots, max_len, ...]``: every slot then pays for the longest possible
sequence whether or not anything lives there, and the slot count — not the
token count — caps concurrency.  Instead the cache is a flat pool of
fixed-size **blocks** (``block_size`` tokens each), shared by every live
sequence, with a per-sequence **page table** mapping logical token index
``j`` to physical block ``table[j // block_size]``.  Memory then scales with
*live tokens*: a 3-token sequence next to a 100-token one holds 1 block, not
a max-length row.

Two-level accounting (all host-side, one lock):

* **reservation** — at admission the engine reserves the worst-case block
  count for the whole stream (``prompt + max_new`` tokens).  ``reserve()``
  refuses when the pool cannot cover every outstanding promise
  (``free + cached < reserved + n``) and the engine sheds the request with
  OVERLOADED — the "no blocks free" admission check.  Reserving up front
  means a sequence admitted once can ALWAYS grow: there is no mid-stream
  out-of-memory, no forced eviction of live pages, no deadlock between
  growing sequences.
* **allocation** — blocks are taken lazily (``grow()``), one at a time, as
  generation actually crosses block boundaries, so ``used`` tracks live
  tokens while the reservation only bounds the worst case.

Cross-request prefix sharing (copy-on-write) sits on top:

* every **full** prompt block registered via ``register_prefix`` gets a
  chain hash ``H_i = blake2b(H_{i-1} || tokens[(i-1)*bs : i*bs])`` — the
  chain encodes the ENTIRE preceding prompt, so a hash match means the
  block's K/V is a pure function of the same token prefix and (because
  chunked prefill reads earlier positions through the page table rather
  than recomputing them) bitwise-valid for any request sharing that
  prefix.  A partial tail block is registered under a **full-prompt** key
  ``(H_F, tail tokens)`` — exact-match only, so a non-block-aligned
  shared prefix can never hit (the hash-collision-on-partial-prefix miss
  the tests pin down).
* ``reserve(..., prompt=, align_tokens=)`` walks the chain, **attaches**
  the longest registered prefix (refcount +1 per sequence per block) and
  reserves only the blocks the sequence might still write — everything
  from the first recomputed chunk onward, so a later copy-on-write fork
  can never run out of memory mid-stream.
* blocks are **refcounted**: ``writable()`` returns the physical block for
  a logical index, forking it first (new private block, caller copies the
  device pages) when the refcount is > 1.  Refcount 1 writes in place —
  registered content below the registered length is append-only-immutable
  so the hash stays valid.
* when a sequence frees, each table entry is decref'd; registered blocks
  whose refcount hits zero are parked in an LRU **cached** pool (contents
  intact, attachable by future requests) and only evicted — registry
  entries dropped, block returned to the free list — when an allocation
  finds the free list empty.  Eviction draws from the cached pool ONLY,
  so a block with live references is never reclaimed.

``allocated_total``/``freed_total`` count per-sequence attach/detach
(attach = +1 allocated, detach = +1 freed, fork = detach old + attach new),
so the tier-1 leak gate ``allocated_total == freed_total`` keeps meaning
"no table retains pages" even when pages are shared.

Block 0 is the **trash block**: dead decode slots in the fixed-shape step
still execute and still scatter their (garbage) K/V somewhere — they all
point at block 0, which is never allocated to a sequence, so a dead slot can
never contaminate a live stream's pages.

The device half (``init_pools``) is a pair of zeros arrays
``[num_layers, num_blocks, block_size, num_heads, head_dim]`` for K and V.
The pools are threaded *functionally* through the decode CachedOps (inputs
-> updated outputs) and the engine worker swaps the handles each step; this
object never holds them, so the accounting lock is never held across an XLA
call.  Thread-safe: every mutable field is guarded by ``_lock``
(docs/CONCURRENCY.md).

Every accounting increment mirrors into the process-wide byte accountant
(``mxnet_tpu.memory_accounting``) under the cache's ``account_region``
label (default: a unique ``"kv:N"``): attach/grow/CoW-attach record
``block_bytes`` allocated, detach/free record it freed — the runtime half
of the mem lint pass (analysis/memory_lint.py), which the ``mem`` stress
scenario cross-checks against ``stats()``'s allocated/freed totals.
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

from ...base import MXNetError

__all__ = ["PagedKVCache", "ReserveResult"]

_CHAIN_SEED = b"mxnet-tpu-kv-prefix-v1"

_REGION_LOCK = threading.Lock()
_REGION_IDS = 0


def _next_account_region():
    """Unique default byte-accountant region label for a new cache."""
    global _REGION_IDS
    with _REGION_LOCK:
        _REGION_IDS += 1
        return "kv:%d" % _REGION_IDS


class ReserveResult:
    """Truthy result of a successful ``reserve`` with a prompt attached.

    ``prefix_tokens`` — prompt positions already materialized in attached
    shared pages; chunked prefill starts there (always a chunk boundary,
    always < len(prompt) so the engine recomputes at least the last chunk
    and owns first-token logits).  ``shared_blocks`` — number of attached
    shared pages.  ``full_hit`` — the entire prompt (including a partial
    tail block) matched; the recomputed last chunk then writes into shared
    pages and triggers copy-on-write forks while other holders are live.
    """

    __slots__ = ("prefix_tokens", "shared_blocks", "full_hit")

    def __init__(self, prefix_tokens=0, shared_blocks=0, full_hit=False):
        self.prefix_tokens = int(prefix_tokens)
        self.shared_blocks = int(shared_blocks)
        self.full_hit = bool(full_hit)

    def __bool__(self):
        return True

    def __repr__(self):
        return ("ReserveResult(prefix_tokens=%d, shared_blocks=%d, "
                "full_hit=%s)" % (self.prefix_tokens, self.shared_blocks,
                                  self.full_hit))


class PagedKVCache:
    def __init__(self, num_layers, num_blocks, block_size, num_heads,
                 head_dim, dtype="float32", account_region=None):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the trash block)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        import numpy as np
        self.num_layers = int(num_layers)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        # one logical block = a K page + a V page across every layer
        self.block_bytes = (2 * self.num_layers * self.block_size
                            * self.num_heads * self.head_dim
                            * np.dtype(dtype).itemsize)
        self.account_region = (str(account_region) if account_region
                               else _next_account_region())
        # re-entrant: the allocation helpers below guard themselves, and
        # the public operations call them with the lock already held
        self._lock = threading.RLock()
        # LIFO free list over allocatable ids 1..num_blocks-1 (0 = trash)
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._tables = {}        # seq_id -> [block ids, logical order]
        self._reservations = {}  # seq_id -> blocks promised but not taken
        self._reserved = 0       # sum of _reservations values
        self._ref = {}           # block id -> live table references
        self._registry = {}      # chain/full key -> block id
        self._block_keys = {}    # block id -> [registry keys]
        self._cached = OrderedDict()  # ref==0 registered blocks, LRU order
        self._allocated_total = 0
        self._freed_total = 0
        self._peak_used = 0
        self._prefix_hits = 0
        self._prefix_blocks_shared = 0
        self._cow_forks = 0
        self._evictions = 0

    # -- device half ----------------------------------------------------
    def pool_shape(self):
        return (self.num_layers, self.num_blocks, self.block_size,
                self.num_heads, self.head_dim)

    def init_pools(self):
        """Fresh zeroed (k_pool, v_pool) NDArray pair."""
        from ... import ndarray as nd
        shape = self.pool_shape()
        return nd.zeros(shape, dtype=self.dtype), \
            nd.zeros(shape, dtype=self.dtype)

    # -- host accounting ------------------------------------------------
    def blocks_for_tokens(self, n_tokens):
        """Blocks covering ``n_tokens`` logical positions."""
        return max(1, -(-int(n_tokens) // self.block_size))

    def _chain_hashes(self, prompt):
        """Chain hash after each full block of ``prompt`` (list of F
        digests) plus the trailing partial-block tokens."""
        bs = self.block_size
        full = len(prompt) // bs
        h = hashlib.blake2b(_CHAIN_SEED, digest_size=16).digest()
        out = []
        for i in range(full):
            block = bytes(bytearray(
                b for t in prompt[i * bs:(i + 1) * bs]
                for b in int(t).to_bytes(4, "little", signed=False)))
            h = hashlib.blake2b(h + block, digest_size=16).digest()
            out.append(h)
        tail = tuple(int(t) for t in prompt[full * bs:])
        return out, tail

    def _account_alloc(self, nblocks=1):
        """Mirror ``nblocks`` page attachments into the byte accountant."""
        from ... import memory_accounting
        memory_accounting.record_alloc(self.block_bytes * nblocks,
                                       self.account_region, count=nblocks)

    def _account_free(self, nblocks=1):
        """Mirror ``nblocks`` page detachments into the byte accountant."""
        from ... import memory_accounting
        memory_accounting.record_free(self.block_bytes * nblocks,
                                      self.account_region, count=nblocks)

    def _take_block_locked(self):
        """Pop a free block, evicting the LRU cached block if none free.
        Eviction only ever touches the ref==0 cached pool, so shared pages
        (refcount >= 1) are never reclaimed."""
        with self._lock:
            if self._free:
                return self._free.pop()
            if not self._cached:
                raise MXNetError(
                    "KV pool exhausted (no free or cached blocks)")
            block, _ = self._cached.popitem(last=False)
            for key in self._block_keys.pop(block, ()):
                self._registry.pop(key, None)
            self._evictions += 1
            return block

    def _attach_locked(self, seq_id, block):
        """Add ``block`` to ``seq_id``'s table, incref, pull from cached."""
        with self._lock:
            ref = self._ref.get(block, 0)
            if ref == 0:
                self._cached.pop(block, None)
            self._ref[block] = ref + 1
            self._tables.setdefault(seq_id, []).append(block)
            self._allocated_total += 1
            self._account_alloc()

    def _used_locked(self):
        with self._lock:
            return ((self.num_blocks - 1) - len(self._free)
                    - len(self._cached))

    def _note_peak_locked(self):
        used = self._used_locked()
        if used > self._peak_used:
            self._peak_used = used

    def reserve(self, seq_id, n_blocks, prompt=None, align_tokens=None):
        """Promise ``n_blocks`` to ``seq_id``; False when the pool cannot
        honor every outstanding promise (the admission shed signal).

        With ``prompt`` (token id sequence) and ``align_tokens`` (the
        engine's chunk size, a multiple of ``block_size``), the call also
        attaches the longest registered shared prefix and returns a
        truthy :class:`ReserveResult` describing the hit; the reservation
        then covers only the writable region (first recomputed chunk
        onward) so shared pages cost no headroom but every potential
        copy-on-write fork is still guaranteed a block."""
        n_blocks = int(n_blocks)
        with self._lock:
            if seq_id in self._reservations or seq_id in self._tables:
                raise MXNetError("sequence %r already holds KV state"
                                 % (seq_id,))
            attach = []
            prefix_tokens = 0
            full_hit = False
            if prompt is not None and len(prompt) > 0:
                bs = self.block_size
                align = int(align_tokens or bs)
                L = len(prompt)
                hashes, tail = self._chain_hashes(prompt)
                matched = []
                for h in hashes:
                    b = self._registry.get(("blk", h))
                    if b is None:
                        break
                    matched.append(b)
                m = len(matched)
                last_chunk = ((L - 1) // align) * align
                if m == len(hashes):
                    tail_block = None
                    if tail:
                        tail_block = self._registry.get(
                            ("full", hashes[-1] if hashes else b"", tail))
                    if tail and tail_block is not None:
                        full_hit = True
                        attach = matched + [tail_block]
                        prefix_tokens = last_chunk
                    elif not tail and m > 0:
                        full_hit = True
                        attach = matched
                        prefix_tokens = last_chunk
                if not full_hit and m > 0:
                    t = min((m * bs // align) * align, last_chunk)
                    prefix_tokens = t
                    attach = matched[:t // bs]
            # reservation covers every block from the first recomputed
            # position onward: private growth AND forks of attached pages
            need = max(0, n_blocks - prefix_tokens // self.block_size)
            if len(self._free) + len(self._cached) - self._reserved < need:
                return False
            for b in attach:
                self._attach_locked(seq_id, b)
            self._reservations[seq_id] = need
            self._reserved += need
            self._note_peak_locked()
            if attach:
                self._prefix_hits += 1
                self._prefix_blocks_shared += len(attach)
            if prompt is not None:
                return ReserveResult(prefix_tokens, len(attach), full_hit)
            return True

    def grow(self, seq_id):
        """Convert one reserved block into an allocated page; returns the
        block id (appended to the sequence's page table)."""
        with self._lock:
            remaining = self._reservations.get(seq_id, 0)
            if remaining < 1:
                raise MXNetError("sequence %r grew past its reservation"
                                 % (seq_id,))
            block = self._take_block_locked()
            self._reservations[seq_id] = remaining - 1
            self._reserved -= 1
            self._tables.setdefault(seq_id, []).append(block)
            self._ref[block] = 1
            self._allocated_total += 1
            self._account_alloc()
            self._note_peak_locked()
            return block

    def ensure_capacity(self, seq_id, n_tokens):
        """Grow ``seq_id`` until its table covers ``n_tokens`` positions."""
        need = self.blocks_for_tokens(n_tokens)
        with self._lock:
            have = len(self._tables.get(seq_id, ()))
        while have < need:
            self.grow(seq_id)
            have += 1

    def writable(self, seq_id, logical_idx):
        """Physical block for ``seq_id``'s logical index, copy-on-write.

        Refcount 1: returns ``(block, None)`` — write in place.  Shared
        (refcount > 1): allocates a private replacement from the
        sequence's reservation, swaps the table entry, and returns
        ``(new_block, old_block)`` — the caller must copy the device
        pages ``old -> new`` before writing."""
        logical_idx = int(logical_idx)
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None or logical_idx >= len(table):
                raise MXNetError("sequence %r has no block at index %d"
                                 % (seq_id, logical_idx))
            block = table[logical_idx]
            if self._ref.get(block, 0) <= 1:
                return block, None
            remaining = self._reservations.get(seq_id, 0)
            if remaining < 1:
                raise MXNetError("sequence %r fork exceeds its reservation"
                                 % (seq_id,))
            new = self._take_block_locked()
            self._reservations[seq_id] = remaining - 1
            self._reserved -= 1
            table[logical_idx] = new
            self._ref[block] -= 1
            self._ref[new] = 1
            self._freed_total += 1       # detached the shared page
            self._account_free()
            self._allocated_total += 1   # attached the private copy
            self._account_alloc()
            self._cow_forks += 1
            self._note_peak_locked()
            return new, block

    def register_prefix(self, seq_id, prompt):
        """Publish ``seq_id``'s prompt pages for cross-request reuse.

        Called once prefill has materialized the prompt's K/V.  Each full
        block gains a chain-hash entry (first writer wins — a duplicate
        recompute keeps its private pages unregistered); a partial tail
        block gains an exact-match full-prompt entry."""
        if prompt is None or len(prompt) == 0:
            return 0
        bs = self.block_size
        hashes, tail = self._chain_hashes(prompt)
        registered = 0
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None:
                raise MXNetError("sequence %r holds no pages to register"
                                 % (seq_id,))
            for i, h in enumerate(hashes):
                key = ("blk", h)
                if key in self._registry:
                    continue
                block = table[i]
                self._registry[key] = block
                self._block_keys.setdefault(block, []).append(key)
                registered += 1
            if tail and hashes:
                key = ("full", hashes[-1], tail)
                block = table[len(hashes)]
                if key not in self._registry:
                    self._registry[key] = block
                    self._block_keys.setdefault(block, []).append(key)
                    registered += 1
        return registered

    def release(self, seq_id):
        """Drop the unconverted remainder of a reservation (request never
        joined, or finished early)."""
        with self._lock:
            self._reserved -= self._reservations.pop(seq_id, 0)

    def free_seq(self, seq_id):
        """Detach every block of ``seq_id`` and drop any remaining
        reservation; returns the number of blocks detached.  A block whose
        refcount drops to zero returns to the free list — unless it is
        registered for prefix reuse, in which case it parks in the cached
        pool (contents intact) until attached again or evicted."""
        with self._lock:
            blocks = self._tables.pop(seq_id, [])
            for block in reversed(blocks):
                ref = self._ref.get(block, 0) - 1
                if ref > 0:
                    self._ref[block] = ref
                    continue
                self._ref.pop(block, None)
                if self._block_keys.get(block):
                    self._cached[block] = True   # MRU end
                else:
                    self._free.append(block)
            self._freed_total += len(blocks)
            if blocks:
                self._account_free(len(blocks))
            self._reserved -= self._reservations.pop(seq_id, 0)
            return len(blocks)

    def blocks_of(self, seq_id):
        """The sequence's allocated page table, unpadded (the exact block
        ids holding its K/V, logical order) — what ``export_stream`` copies."""
        with self._lock:
            return list(self._tables.get(seq_id, ()))

    def ref_count(self, block):
        """Live table references to ``block`` (0 = free or cached)."""
        with self._lock:
            return self._ref.get(int(block), 0)

    def table(self, seq_id, width):
        """The sequence's page table padded to ``width`` entries with the
        trash block (0); entries past the live length are never unmasked."""
        with self._lock:
            blocks = list(self._tables.get(seq_id, ()))
        if len(blocks) > width:
            raise MXNetError("page table of %r (%d blocks) exceeds width %d"
                             % (seq_id, len(blocks), width))
        return blocks + [0] * (width - len(blocks))

    def used(self):
        """Blocks held by at least one live table (each counted once)."""
        with self._lock:
            return self._used_locked()

    def available_unreserved(self):
        """Blocks neither held by a table nor promised (the admission
        signal): free + evictable-cached - reserved.  Shared pages are
        held once no matter how many sequences reference them, so fleet
        headroom counts them once."""
        with self._lock:
            return len(self._free) + len(self._cached) - self._reserved

    def capacity(self):
        """Total allocatable blocks (trash block excluded)."""
        return self.num_blocks - 1

    def stats(self):
        with self._lock:
            shared_now = sum(1 for r in self._ref.values() if r > 1)
            return {
                "num_blocks": self.num_blocks - 1,   # allocatable
                "block_size": self.block_size,
                "block_bytes": self.block_bytes,
                "used": self._used_locked(),
                "free": len(self._free),
                "reserved": self._reserved,
                "live_sequences": len(self._tables),
                "allocated_total": self._allocated_total,
                "freed_total": self._freed_total,
                "peak_used": self._peak_used,
                "prefix_hits": self._prefix_hits,
                "prefix_blocks_shared": self._prefix_blocks_shared,
                "cow_forks": self._cow_forks,
                "cached_blocks": len(self._cached),
                "shared_blocks_now": shared_now,
                "evictions": self._evictions,
            }
