"""Paged KV cache: a device-resident block pool + per-sequence page tables.

The whole point of continuous batching collapses if KV memory is laid out
``[max_slots, max_len, ...]``: every slot then pays for the longest possible
sequence whether or not anything lives there, and the slot count — not the
token count — caps concurrency.  Instead the cache is a flat pool of
fixed-size **blocks** (``block_size`` tokens each), shared by every live
sequence, with a per-sequence **page table** mapping logical token index
``j`` to physical block ``table[j // block_size]``.  Memory then scales with
*live tokens*: a 3-token sequence next to a 100-token one holds 1 block, not
a max-length row.

Two-level accounting (all host-side, one lock):

* **reservation** — at admission the engine reserves the worst-case block
  count for the whole stream (``prompt + max_new`` tokens).  ``reserve()``
  refuses when the pool cannot cover every outstanding promise
  (``free < reserved + n``) and the engine sheds the request with
  OVERLOADED — the "no blocks free" admission check.  Reserving up front
  means a sequence admitted once can ALWAYS grow: there is no mid-stream
  out-of-memory, no eviction, no deadlock between growing sequences.
* **allocation** — blocks are taken lazily (``grow()``), one at a time, as
  generation actually crosses block boundaries, so ``used`` tracks live
  tokens while the reservation only bounds the worst case.

Block 0 is the **trash block**: dead decode slots in the fixed-shape step
still execute and still scatter their (garbage) K/V somewhere — they all
point at block 0, which is never allocated to a sequence, so a dead slot can
never contaminate a live stream's pages.

The device half (``init_pools``) is a pair of zeros arrays
``[num_layers, num_blocks, block_size, num_heads, head_dim]`` for K and V.
The pools are threaded *functionally* through the decode CachedOps (inputs
-> updated outputs) and the engine worker swaps the handles each step; this
object never holds them, so the accounting lock is never held across an XLA
call.  Thread-safe: every mutable field is guarded by ``_lock``
(docs/CONCURRENCY.md).
"""
from __future__ import annotations

import threading

from ...base import MXNetError

__all__ = ["PagedKVCache"]


class PagedKVCache:
    def __init__(self, num_layers, num_blocks, block_size, num_heads,
                 head_dim, dtype="float32"):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the trash block)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_layers = int(num_layers)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        self._lock = threading.Lock()
        # LIFO free list over allocatable ids 1..num_blocks-1 (0 = trash)
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._tables = {}        # seq_id -> [block ids, logical order]
        self._reservations = {}  # seq_id -> blocks promised but not taken
        self._reserved = 0       # sum of _reservations values
        self._allocated_total = 0
        self._freed_total = 0
        self._peak_used = 0

    # -- device half ----------------------------------------------------
    def pool_shape(self):
        return (self.num_layers, self.num_blocks, self.block_size,
                self.num_heads, self.head_dim)

    def init_pools(self):
        """Fresh zeroed (k_pool, v_pool) NDArray pair."""
        from ... import ndarray as nd
        shape = self.pool_shape()
        return nd.zeros(shape, dtype=self.dtype), \
            nd.zeros(shape, dtype=self.dtype)

    # -- host accounting ------------------------------------------------
    def blocks_for_tokens(self, n_tokens):
        """Blocks covering ``n_tokens`` logical positions."""
        return max(1, -(-int(n_tokens) // self.block_size))

    def reserve(self, seq_id, n_blocks):
        """Promise ``n_blocks`` to ``seq_id``; False when the pool cannot
        honor every outstanding promise (the admission shed signal)."""
        n_blocks = int(n_blocks)
        with self._lock:
            if seq_id in self._reservations or seq_id in self._tables:
                raise MXNetError("sequence %r already holds KV state"
                                 % (seq_id,))
            if len(self._free) - self._reserved < n_blocks:
                return False
            self._reservations[seq_id] = n_blocks
            self._reserved += n_blocks
            return True

    def grow(self, seq_id):
        """Convert one reserved block into an allocated page; returns the
        block id (appended to the sequence's page table)."""
        with self._lock:
            remaining = self._reservations.get(seq_id, 0)
            if remaining < 1:
                raise MXNetError("sequence %r grew past its reservation"
                                 % (seq_id,))
            block = self._free.pop()
            self._reservations[seq_id] = remaining - 1
            self._reserved -= 1
            self._tables.setdefault(seq_id, []).append(block)
            self._allocated_total += 1
            used = (self.num_blocks - 1) - len(self._free)
            if used > self._peak_used:
                self._peak_used = used
            return block

    def ensure_capacity(self, seq_id, n_tokens):
        """Grow ``seq_id`` until its table covers ``n_tokens`` positions."""
        need = self.blocks_for_tokens(n_tokens)
        with self._lock:
            have = len(self._tables.get(seq_id, ()))
        while have < need:
            self.grow(seq_id)
            have += 1

    def release(self, seq_id):
        """Drop the unconverted remainder of a reservation (request never
        joined, or finished early)."""
        with self._lock:
            self._reserved -= self._reservations.pop(seq_id, 0)

    def free_seq(self, seq_id):
        """Return every block of ``seq_id`` to the pool and drop any
        remaining reservation; returns the number of blocks freed."""
        with self._lock:
            blocks = self._tables.pop(seq_id, [])
            self._free.extend(reversed(blocks))
            self._freed_total += len(blocks)
            self._reserved -= self._reservations.pop(seq_id, 0)
            return len(blocks)

    def blocks_of(self, seq_id):
        """The sequence's allocated page table, unpadded (the exact block
        ids holding its K/V, logical order) — what ``export_stream`` copies."""
        with self._lock:
            return list(self._tables.get(seq_id, ()))

    def table(self, seq_id, width):
        """The sequence's page table padded to ``width`` entries with the
        trash block (0); entries past the live length are never unmasked."""
        with self._lock:
            blocks = list(self._tables.get(seq_id, ()))
        if len(blocks) > width:
            raise MXNetError("page table of %r (%d blocks) exceeds width %d"
                             % (seq_id, len(blocks), width))
        return blocks + [0] * (width - len(blocks))

    def used(self):
        with self._lock:
            return (self.num_blocks - 1) - len(self._free)

    def available_unreserved(self):
        """Blocks neither allocated nor promised (the admission signal)."""
        with self._lock:
            return len(self._free) - self._reserved

    def capacity(self):
        """Total allocatable blocks (trash block excluded)."""
        return self.num_blocks - 1

    def stats(self):
        with self._lock:
            used = (self.num_blocks - 1) - len(self._free)
            return {
                "num_blocks": self.num_blocks - 1,   # allocatable
                "block_size": self.block_size,
                "used": used,
                "free": len(self._free),
                "reserved": self._reserved,
                "live_sequences": len(self._tables),
                "allocated_total": self._allocated_total,
                "freed_total": self._freed_total,
                "peak_used": self._peak_used,
            }
