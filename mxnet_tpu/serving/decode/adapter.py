"""Gluon-block -> paged-KV decode-model adapter.

The decode engine speaks the paged-KV contract (model.py), not the Gluon
batch-forward convention — a Gluon causal LM cannot serve as-is because
an autoregressive step must read and write paged pool state.  What a
trained block DOES carry is everything the contract kernels need: the
weights.  :class:`GluonCausalLMAdapter` turns any hybridizable or
**exported** (``HybridBlock.export`` -> ``SymbolBlock.imports``) causal
LM of the reference architecture into a full contract model:

* **role discovery** maps ``collect_params()`` names onto kernel roles by
  suffix — ``embed_weight``, ``pos_weight`` and per-layer
  ``l{i}_{wq|wk|wv|wo|w1|w2}_weight`` (any block/name-scope prefix) — or
  through an explicit ``layer_map`` when a block names things its own
  way.  Missing or ambiguous roles raise ValueErrors naming the
  candidates, never a shape error inside a compiled kernel.
* **live handles**: ``param_dict()`` returns each ``Parameter.data()``
  NDArray, so the engine's CachedOps see weight updates the same way a
  hybridized block does — no copies, no snapshots.
* **layout adaptation happens inside the trace**: Gluon ``Dense`` stores
  ``[units, in_units]`` (FullyConnected computes ``x @ W.T``) while the
  contract kernels take ``[in, out]``; the adapter transposes at trace
  time, so XLA folds the transpose into the matmul's dimension numbers
  and the live handle is still the block's own storage.
* the serving kernels are the PROVEN ones — the adapter delegates to
  ``TinyCausalLM``'s prefill/decode/chunk/verify/propose suite over the
  adapted weights, so the exactness contract (exact-zero masking,
  row-independence, fixed signatures) holds by construction and the
  whole composed stack (prefix cache, CoW, chunked prefill, speculative
  verify, export/import handoff, ShardedDecodeModel) applies unchanged.
* ``partition_specs()`` emits Gluon-layout specs per layer kind, so
  ``ShardedDecodeModel`` shards adapted weights exactly like native
  contract models (attention/wide projections on the ``tp`` axis).

``num_heads`` must be supplied — a weight file cannot reveal how a
square attention projection splits into heads.  Everything else
(vocab/hidden/layer count/max_len) is read off the discovered shapes.

:class:`TinyGluonLM` is the in-tree demo block: the same pre-norm
transformer as ``TinyCausalLM`` written as a ``HybridBlock`` over
``F.Embedding``/``F.FullyConnected``/``F.batch_dot`` symbol-compatible
ops, so it hybridizes, exports and re-imports — the export round-trip
the adapter tests serve end-to-end.
"""
from __future__ import annotations

import re

from ...gluon.block import HybridBlock
from .model import TinyCausalLM

__all__ = ["GluonCausalLMAdapter", "TinyGluonLM", "discover_roles",
           "copy_reference_weights", "DENSE_ROLES"]

DENSE_ROLES = ("wq", "wk", "wv", "wo", "w1", "w2")

_LAYER_RE = re.compile(r"(?:^|_)l(\d+)_(wq|wk|wv|wo|w1|w2)_weight$")
_EMBED_RE = re.compile(r"(?:^|_)embed_weight$")
_POS_RE = re.compile(r"(?:^|_)pos_weight$")


def discover_roles(names, layer_map=None):
    """Map parameter names onto kernel roles by suffix.

    Returns ``{role: name}`` with roles ``embed``, ``pos`` and
    ``l{i}_{wq|...}``.  ``layer_map`` entries override discovery (and are
    checked against ``names``).  Raises ValueError naming every candidate
    on ambiguity and the missing role otherwise.
    """
    roles = {}
    for name in names:
        m = _LAYER_RE.search(name)
        if m:
            role = "l%d_%s" % (int(m.group(1)), m.group(2))
        elif _EMBED_RE.search(name):
            role = "embed"
        elif _POS_RE.search(name):
            role = "pos"
        else:
            continue
        if role in roles:
            raise ValueError(
                "GluonCausalLMAdapter: role %r is ambiguous: both %r and "
                "%r match; pass layer_map={...} to pick one"
                % (role, roles[role], name))
        roles[role] = name
    if layer_map:
        known = set(names)
        for role, name in layer_map.items():
            if name not in known:
                raise ValueError(
                    "GluonCausalLMAdapter: layer_map maps role %r to %r, "
                    "which is not among the block's parameters"
                    % (role, name))
            roles[role] = name
    for role in ("embed", "pos"):
        if role not in roles:
            raise ValueError(
                "GluonCausalLMAdapter: no parameter matches role %r "
                "(expected a name ending in %r_weight); found %r"
                % (role, role, sorted(names)))
    return roles


class GluonCausalLMAdapter:
    """Serve a Gluon causal LM through the paged-KV decode contract."""

    # dense roles live in Gluon's [units, in] layout (the transpose of the
    # contract's [in, units]); ShardedDecodeModel's compute-parallel
    # kernels read this attr and transpose LOCAL shards back at trace time
    param_layout = "gluon"

    def __init__(self, block, num_heads, eos_id=None, layer_map=None):
        params = {name: p for name, p in block.collect_params().items()}
        roles = discover_roles(list(params), layer_map)

        layers = set()
        for role in roles:
            m = re.match(r"l(\d+)_", role)
            if m:
                layers.add(int(m.group(1)))
        num_layers = (max(layers) + 1) if layers else 0
        if not num_layers:
            raise ValueError(
                "GluonCausalLMAdapter: no l{i}_{wq|wk|wv|wo|w1|w2}_weight "
                "layer parameters found; found %r" % (sorted(params),))
        for l in range(num_layers):
            for r in DENSE_ROLES:
                if "l%d_%s" % (l, r) not in roles:
                    raise ValueError(
                        "GluonCausalLMAdapter: layer %d is missing role %r "
                        "(layers must be contiguous and complete; found %r)"
                        % (l, r, sorted(roles)))

        self._role_params = {role: params[name]
                             for role, name in roles.items()}
        self.role_names = dict(roles)

        embed = self._role_params["embed"].data()
        pos = self._role_params["pos"].data()
        if len(embed.shape) != 2 or len(pos.shape) != 2:
            raise ValueError(
                "GluonCausalLMAdapter: embed %r / pos %r must be rank-2 "
                "[vocab, hidden] / [max_len, hidden]"
                % (embed.shape, pos.shape))
        vocab_size, hidden = embed.shape
        if pos.shape[1] != hidden:
            raise ValueError(
                "GluonCausalLMAdapter: pos hidden size %d does not match "
                "embed hidden size %d" % (pos.shape[1], hidden))
        if hidden % int(num_heads):
            raise ValueError(
                "GluonCausalLMAdapter: hidden size %d is not divisible by "
                "num_heads %d" % (hidden, int(num_heads)))
        ff = None
        for l in range(num_layers):
            for r in ("wq", "wk", "wv", "wo"):
                shp = self._role_params["l%d_%s" % (l, r)].data().shape
                if tuple(shp) != (hidden, hidden):
                    raise ValueError(
                        "GluonCausalLMAdapter: l%d_%s has shape %r, want "
                        "[hidden, hidden] = %r"
                        % (l, r, tuple(shp), (hidden, hidden)))
            w1 = self._role_params["l%d_w1" % l].data().shape
            w2 = self._role_params["l%d_w2" % l].data().shape
            if len(w1) != 2 or w1[1] != hidden:
                raise ValueError(
                    "GluonCausalLMAdapter: l%d_w1 has shape %r, want the "
                    "Gluon [ff, hidden] layout with hidden=%d"
                    % (l, tuple(w1), hidden))
            if ff is None:
                ff = w1[0]
            if tuple(w1) != (ff, hidden) or tuple(w2) != (hidden, ff):
                raise ValueError(
                    "GluonCausalLMAdapter: layer %d MLP shapes w1=%r w2=%r "
                    "are inconsistent with ff width %d"
                    % (l, tuple(w1), tuple(w2), ff))

        self.vocab_size = int(vocab_size)
        self.hidden = int(hidden)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = self.hidden // self.num_heads
        self.max_len = int(pos.shape[0])
        self.ff = int(ff)
        self.eos_id = eos_id
        # kernel skeleton: TinyCausalLM's fns read only geometry attrs and
        # the param dict they are handed, so a no-__init__ instance IS the
        # proven kernel suite over the adapted weights
        kern = TinyCausalLM.__new__(TinyCausalLM)
        kern.vocab_size = self.vocab_size
        kern.hidden = self.hidden
        kern.num_layers = self.num_layers
        kern.num_heads = self.num_heads
        kern.head_dim = self.head_dim
        kern.max_len = self.max_len
        kern.eos_id = eos_id
        kern.context_attention = None
        kern._params = {}
        self._kern = kern

    # -- contract surface ------------------------------------------------
    def param_dict(self):
        """Live Gluon Parameter storage, keyed by role."""
        return {role: p.data() for role, p in self._role_params.items()}

    def _contract(self, p):
        """Adapt Gluon-layout weights to the kernel layout inside the
        trace: Dense kernels are ``[units, in]`` (y = x @ W.T), the
        contract kernels contract ``x @ W`` — transpose here so XLA folds
        it into the dot and the live handles stay untouched."""
        out = {"embed": p["embed"], "pos": p["pos"]}
        for l in range(self.num_layers):
            for r in DENSE_ROLES:
                key = "l%d_%s" % (l, r)
                out[key] = p[key].T
        return out

    def prefill_fn(self, p, tokens, length, table, k_pool, v_pool):
        return self._kern.prefill_fn(self._contract(p), tokens, length,
                                     table, k_pool, v_pool)

    def decode_fn(self, p, tokens, positions, tables, k_pool, v_pool):
        return self._kern.decode_fn(self._contract(p), tokens, positions,
                                    tables, k_pool, v_pool)

    def chunk_prefill_fn(self, p, tokens, start, length, table, k_pool,
                         v_pool):
        return self._kern.chunk_prefill_fn(self._contract(p), tokens, start,
                                           length, table, k_pool, v_pool)

    def verify_fn(self, p, tokens, positions, valids, tables, k_pool,
                  v_pool):
        return self._kern.verify_fn(self._contract(p), tokens, positions,
                                    valids, tables, k_pool, v_pool)

    def propose_fn(self, p, tokens, positions, tables, k_pool, v_pool,
                   num_tokens):
        return self._kern.propose_fn(self._contract(p), tokens, positions,
                                     tables, k_pool, v_pool, num_tokens)

    def partition_specs(self):
        """Weight sharding for ShardedDecodeModel, in the GLUON layout:
        q/k/v and the MLP up-projection split their ``units`` (head/wide)
        axis over 'tp'; the output projections split the matching input
        axis; embed/pos split the hidden axis."""
        from jax.sharding import PartitionSpec as P
        specs = {"embed": P(None, "tp"), "pos": P(None, "tp")}
        for l in range(self.num_layers):
            specs["l%d_wq" % l] = P("tp", None)
            specs["l%d_wk" % l] = P("tp", None)
            specs["l%d_wv" % l] = P("tp", None)
            specs["l%d_wo" % l] = P(None, "tp")
            specs["l%d_w1" % l] = P("tp", None)
            specs["l%d_w2" % l] = P(None, "tp")
        return specs


# ---------------------------------------------------------------------------
# demo block
# ---------------------------------------------------------------------------

class TinyGluonLM(HybridBlock):
    """The ``TinyCausalLM`` architecture as an exportable HybridBlock.

    Forward maps tokens ``[B, T]`` to logits ``[B, T, V]`` through
    symbol-compatible ops only (Embedding, FullyConnected, batch_dot,
    softmax, arange/slice_like for the causal mask), so the block
    hybridizes AND ``export()``s; ``SymbolBlock.imports`` of the result
    re-serves through :class:`GluonCausalLMAdapter` with bit-identical
    weights.  Parameters carry the adapter's role names.  The batch
    forward masks with -1e30 (exp underflows to exact zero after the
    max-shift) — serving exactness still comes from the adapter's paged
    kernels, not this forward.
    """

    def __init__(self, vocab_size=48, hidden=32, num_layers=2, num_heads=2,
                 max_len=128, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if hidden % num_heads:
            raise ValueError("hidden must divide into num_heads")
        self._vocab = int(vocab_size)
        self._hidden = int(hidden)
        self._layers = int(num_layers)
        self._heads = int(num_heads)
        self._max_len = int(max_len)
        shapes = {"embed_weight": (self._vocab, self._hidden),
                  "pos_weight": (self._max_len, self._hidden)}
        for l in range(self._layers):
            for r in ("wq", "wk", "wv", "wo"):
                shapes["l%d_%s_weight" % (l, r)] = (self._hidden,
                                                    self._hidden)
            shapes["l%d_w1_weight" % l] = (2 * self._hidden, self._hidden)
            shapes["l%d_w2_weight" % l] = (self._hidden, 2 * self._hidden)
        for name, shape in shapes.items():
            setattr(self, name, self.params.get(name, shape=shape))

    def _rms(self, F, x):
        denom = F.sqrt(F.mean(x * x, axis=-1, keepdims=True) + 1e-6)
        return F.broadcast_div(x, denom)

    def hybrid_forward(self, F, tokens, **params):
        H, nh = self._hidden, self._heads
        d = H // nh
        # [T, B, H] layout throughout: slice_like against axis 0 gives the
        # length-T position slice without knowing T at graph-build time
        emb = F.Embedding(F.transpose(tokens, axes=(1, 0)),
                          params["embed_weight"],
                          input_dim=self._vocab, output_dim=H)
        pos = F.slice_like(params["pos_weight"], emb, axes=(0,))
        h = F.broadcast_add(emb, F.expand_dims(pos, axis=1))
        ar = F.slice_like(F.arange(start=0, stop=self._max_len), emb,
                          axes=(0,))
        # attend = 1.0 where query position i >= key position j
        attend = F.broadcast_greater_equal(F.expand_dims(ar, axis=1),
                                           F.expand_dims(ar, axis=0))
        negmask = F.expand_dims((attend - 1.0) * 1e30, axis=0)  # [1, T, T]
        for l in range(self._layers):
            x = self._rms(F, h)
            qkv = []
            for r in ("wq", "wk", "wv"):
                y = F.FullyConnected(x, params["l%d_%s_weight" % (l, r)],
                                     num_hidden=H, no_bias=True,
                                     flatten=False)       # [T, B, H]
                y = F.reshape(y, shape=(0, 0, nh, d))
                y = F.transpose(y, axes=(1, 2, 0, 3))     # [B, nh, T, d]
                qkv.append(F.reshape(y, shape=(-3, -2)))  # [B*nh, T, d]
            q, k, v = qkv
            scores = F.batch_dot(q, k, transpose_b=True) / float(d) ** 0.5
            w = F.softmax(F.broadcast_add(scores, negmask), axis=-1)
            att = F.batch_dot(w, v)                       # [B*nh, T, d]
            att = F.reshape(att, shape=(-4, -1, nh, 0, 0))
            att = F.transpose(att, axes=(2, 0, 1, 3))     # [T, B, nh, d]
            att = F.reshape(att, shape=(0, 0, -3))
            h = h + F.FullyConnected(att, params["l%d_wo_weight" % l],
                                     num_hidden=H, no_bias=True,
                                     flatten=False)
            g = F.FullyConnected(self._rms(F, h),
                                 params["l%d_w1_weight" % l],
                                 num_hidden=2 * H, no_bias=True,
                                 flatten=False)
            h = h + F.FullyConnected(F.LeakyReLU(g, act_type="gelu"),
                                     params["l%d_w2_weight" % l],
                                     num_hidden=H, no_bias=True,
                                     flatten=False)
        logits = F.FullyConnected(self._rms(F, h), params["embed_weight"],
                                  num_hidden=self._vocab, no_bias=True,
                                  flatten=False)          # [T, B, V]
        return F.transpose(logits, axes=(1, 0, 2))


def copy_reference_weights(block, ref):
    """Load a ``TinyCausalLM``'s weights into a role-named Gluon block,
    transposing dense kernels into the Gluon ``[units, in]`` layout.

    The bitwise test fixture: after this, ``GluonCausalLMAdapter(block,
    ref.num_heads)`` computes with value-identical arrays to ``ref``
    (transpose of a transpose), so adapted serving must reproduce the
    native model's streams exactly.
    """
    params = {name: p for name, p in block.collect_params().items()}
    roles = discover_roles(list(params))
    src = ref.param_dict()
    for role, name in roles.items():
        val = src[role]
        if role not in ("embed", "pos"):
            val = val.T
        params[name].set_data(val)
