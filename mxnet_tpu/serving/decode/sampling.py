"""Deterministic host-side sampling for decode streams.

Temperature / top-k / top-p act on the logits the engine already fetched
for the step, entirely in float64 numpy on the host — never inside the
compiled kernels — so turning sampling on cannot change a single compiled
signature, and the greedy path (temperature 0) stays bit-identical to the
pre-sampling engine.

Determinism contract (what makes chaos runs and the sequential oracle
replayable):

* every sampled stream owns a private ``np.random.RandomState(seed)``;
  one uniform draw per emitted token, nothing else touches it;
* an explicit ``seed`` makes the stream a pure function of
  (params, prompt, sampling options): the same submission replays the
  same tokens on a fresh engine, a restarted engine, or the sequential
  ``generate_reference`` oracle;
* ``seed=None`` derives one from the framework stream
  (``random.derived_numpy_rng()``) — reproducible under
  ``mx.random.seed(n)``, and recorded on the stream so the draw sequence
  is still replayable after the fact;
* tie-breaks are pinned: candidate order comes from a *stable* descending
  sort, the inverse-CDF walk uses ``searchsorted`` on a float64 cumsum —
  no platform-dependent argmax/argsort ambiguity;
* handoff snapshots carry ``(seed, draws)``; the importer rebuilds the
  RandomState and burns ``draws`` uniforms, so a migrated stream
  continues the exact draw sequence it would have used uninterrupted.
"""
from __future__ import annotations

import numpy as np

__all__ = ["SamplingParams", "StreamSampler"]


class SamplingParams:
    """Validated per-stream sampling options.

    ``temperature == 0`` means greedy (argmax); ``top_k == 0`` and
    ``top_p == 1`` disable their filters.  Raises ``ValueError`` on
    out-of-range values — the engine maps that to INVALID_INPUT.
    """

    __slots__ = ("temperature", "top_k", "top_p", "seed")

    def __init__(self, temperature=0.0, top_k=0, top_p=1.0, seed=None):
        temperature = float(temperature)
        top_k = int(top_k)
        top_p = float(top_p)
        if not temperature >= 0.0:
            raise ValueError("temperature must be >= 0, got %r"
                             % (temperature,))
        if top_k < 0:
            raise ValueError("top_k must be >= 0, got %r" % (top_k,))
        if not 0.0 < top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1], got %r" % (top_p,))
        if seed is not None:
            seed = int(seed)
            if not 0 <= seed < 2 ** 31:
                raise ValueError("seed must be in [0, 2**31), got %r"
                                 % (seed,))
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.seed = seed

    @property
    def greedy(self):
        return self.temperature == 0.0

    def as_dict(self):
        return {"temperature": self.temperature, "top_k": self.top_k,
                "top_p": self.top_p, "seed": self.seed}


def resolve_seed(params):
    """The stream's effective seed: the explicit one, or a fresh derivation
    from the framework RNG (reproducible under ``mx.random.seed``)."""
    if params.seed is not None:
        return int(params.seed)
    from ... import random as _random
    return int(_random.derived_numpy_rng().randint(0, 2 ** 31 - 1))


class StreamSampler:
    """Per-stream deterministic sampler: one uniform draw per token."""

    __slots__ = ("params", "seed", "draws", "_rng")

    def __init__(self, params, seed=None):
        self.params = params
        self.seed = int(seed if seed is not None else resolve_seed(params))
        self.draws = 0
        self._rng = np.random.RandomState(self.seed)

    @classmethod
    def restore(cls, params, seed, draws):
        """Rebuild a sampler mid-stream (handoff import): burn ``draws``
        uniforms so the next draw continues the original sequence."""
        s = cls(params, seed=seed)
        draws = int(draws)
        if draws > 0:
            s._rng.random_sample(draws)
            s.draws = draws
        return s

    def state(self):
        return {"seed": self.seed, "draws": self.draws}

    def sample(self, logits):
        """One token from a float32 logits row; float64 math throughout so
        the distribution (and therefore the replay) is platform-stable."""
        p = self.params
        if p.temperature == 0.0:
            return int(np.argmax(logits))
        x = np.asarray(logits, np.float64) / p.temperature
        x -= x.max()
        probs = np.exp(x)
        probs /= probs.sum()
        order = np.argsort(-probs, kind="stable")
        if p.top_k > 0:
            order = order[:p.top_k]
        if p.top_p < 1.0:
            cum = np.cumsum(probs[order])
            keep = int(np.searchsorted(cum, p.top_p, side="left")) + 1
            order = order[:keep]
        kept = probs[order]
        kept /= kept.sum()
        u = self._rng.random_sample()
        self.draws += 1
        idx = int(np.searchsorted(np.cumsum(kept), u, side="right"))
        return int(order[min(idx, len(order) - 1)])
