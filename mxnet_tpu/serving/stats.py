"""Serving observability: per-model counters + latency percentiles.

Two sinks, one writer: every event updates (1) plain numeric fields read by
``ModelServer.stats()`` (always on, lock-protected) and (2) a ``serving``
profiler Domain's Counters — queue depth, batch latency, shed count — so a
``profiler.dump()`` trace shows server activity on the same timeline as op
spans.  Counter writes are gated on ``profiler.profiling_active()``: each
``Counter.set_value`` appends a trace event, and an ungated per-request
update would grow the event buffer without bound in a long-lived server.
"""
from __future__ import annotations

import threading

from .. import profiler

__all__ = ["ModelStats", "LatencyWindow", "stream_tpot_ms",
           "goodput_under_slo"]


class LatencyWindow:
    """Ring buffer of the last ``capacity`` latencies, for percentiles."""

    def __init__(self, capacity=2048):
        self._cap = int(capacity)
        self._buf = []
        self._next = 0

    def add(self, ms):
        if len(self._buf) < self._cap:
            self._buf.append(ms)
        else:
            self._buf[self._next] = ms
            self._next = (self._next + 1) % self._cap

    def percentiles(self, ps=(50, 95, 99)):
        """{"p50": ms, ...} over the window (zeros when empty)."""
        if not self._buf:
            return {"p%d" % p: 0.0 for p in ps}
        ordered = sorted(self._buf)
        out = {}
        for p in ps:
            idx = min(len(ordered) - 1, int(round(p / 100.0 * (len(ordered) - 1))))
            out["p%d" % p] = ordered[idx]
        return out


def stream_tpot_ms(latency_ms, ttft_ms, tokens):
    """Time-per-output-token of one finished stream: the decode-phase
    latency (total minus time-to-first-token) spread over the tokens
    after the first.  None when the stream has fewer than two tokens or
    is missing either timestamp — a one-token stream has no decode phase
    to measure."""
    if tokens is None or int(tokens) < 2:
        return None
    if latency_ms is None or ttft_ms is None:
        return None
    return max(0.0, float(latency_ms) - float(ttft_ms)) / (int(tokens) - 1)


def goodput_under_slo(rows, slo_ttft_ms=None, slo_tpot_ms=None):
    """Goodput accounting over finished streams: how many completed OK
    *and* met every configured latency SLO.

    ``rows`` is an iterable of per-stream dicts with keys ``status``
    (the server.py vocabulary), ``ttft_ms``, ``latency_ms`` and
    ``tokens`` (count).  A ``None`` SLO is unchecked.  Returns::

        {"total": all rows, "ok": OK rows, "good": OK rows within SLO,
         "ttft_violations": OK rows past slo_ttft_ms,
         "tpot_violations": OK rows past slo_tpot_ms,
         "ttft_ms": {"p50": ..., "p99": ...},   # over OK rows
         "tpot_ms": {"p50": ..., "p99": ...}}   # over OK rows with >= 2 tokens

    The rate (goodput per second) is the caller's division: only the
    bench knows the open-loop window the rows arrived in."""
    total = ok = good = ttft_bad = tpot_bad = 0
    ttft_w, tpot_w = LatencyWindow(), LatencyWindow()
    for row in rows:
        total += 1
        if row.get("status") != "OK":
            continue
        ok += 1
        ttft = row.get("ttft_ms")
        tpot = stream_tpot_ms(row.get("latency_ms"), ttft,
                              row.get("tokens"))
        if ttft is not None:
            ttft_w.add(float(ttft))
        if tpot is not None:
            tpot_w.add(tpot)
        meets = True
        if slo_ttft_ms is not None and (ttft is None
                                        or ttft > slo_ttft_ms):
            ttft_bad += 1
            meets = False
        if slo_tpot_ms is not None and tpot is not None \
                and tpot > slo_tpot_ms:
            tpot_bad += 1
            meets = False
        if meets:
            good += 1
    return {
        "total": total,
        "ok": ok,
        "good": good,
        "ttft_violations": ttft_bad,
        "tpot_violations": tpot_bad,
        "ttft_ms": ttft_w.percentiles(ps=(50, 99)),
        "tpot_ms": tpot_w.percentiles(ps=(50, 99)),
    }


class ModelStats:
    """All counters for one loaded model.  Thread-safe."""

    def __init__(self, model_name):
        self._lock = threading.Lock()
        self.requests = 0        # admitted submissions
        self.ok = 0
        self.timeouts = 0
        self.shed = 0            # rejected: queue full
        self.invalid = 0         # rejected: shape not in the bucket menu
        self.errors = 0
        # UNAVAILABLE is split like shed/invalid vs the terminal counters:
        # `unavailable` counts ADMITTED requests drained at teardown (they
        # are part of `requests`, so conservation reads requests == ok +
        # timeouts + errors + unavailable); `unavailable_rejected` counts
        # fast admission rejections (breaker open / shutting down), which
        # — like shed — never enter `requests`
        self.unavailable = 0
        self.unavailable_rejected = 0
        self.retries = 0         # transient execute failures absorbed
        self.batches = 0
        self.batched_requests = 0   # real rows executed
        self.padded_rows = 0        # ladder pad rows executed
        self.queue_depth = 0
        self._req_lat = LatencyWindow()
        self._batch_lat = LatencyWindow()
        domain = profiler.Domain("serving")
        self._c_queue = domain.new_counter("%s:queue_depth" % model_name)
        self._c_batch_ms = domain.new_counter("%s:batch_ms" % model_name)
        self._c_shed = domain.new_counter("%s:shed" % model_name)
        # breaker/health on the same trace timeline: 0 closed, 1 half-open,
        # 2 open — a dump shows exactly when the model went dark and came
        # back, next to the queue-depth/batch-latency collapse that caused it
        self._c_breaker = domain.new_counter("%s:breaker_state" % model_name)
        self._c_unavail = domain.new_counter("%s:unavailable" % model_name)

    # -- event hooks ----------------------------------------------------
    def on_queue_depth(self, depth):
        with self._lock:
            self.queue_depth = depth
        if profiler.profiling_active():
            self._c_queue.set_value(depth)

    def on_admitted(self):
        with self._lock:
            self.requests += 1

    def on_shed(self):
        with self._lock:
            self.shed += 1
            count = self.shed
        if profiler.profiling_active():
            self._c_shed.set_value(count)

    def on_invalid(self):
        with self._lock:
            self.invalid += 1

    def on_unavailable(self, rejected=False):
        """An UNAVAILABLE outcome.  ``rejected=True`` for fast admission
        rejections (breaker open / shutting down — the request never
        entered the queue); False for an admitted request terminated by
        teardown."""
        with self._lock:
            if rejected:
                self.unavailable_rejected += 1
            else:
                self.unavailable += 1
            count = self.unavailable + self.unavailable_rejected
        if profiler.profiling_active():
            self._c_unavail.set_value(count)

    def on_retry(self):
        """One transient execute failure absorbed by the retry envelope."""
        with self._lock:
            self.retries += 1

    def on_breaker_state(self, state):
        """Emit a breaker transition onto the profiler timeline (the
        authoritative open/rejection counts live in the breaker's own
        snapshot — one source, no second copy to drift)."""
        if profiler.profiling_active():
            self._c_breaker.set_value(
                {"closed": 0, "half_open": 1, "open": 2}.get(state, 0))

    def on_batch(self, n_real, bucket, latency_ms):
        with self._lock:
            self.batches += 1
            self.batched_requests += n_real
            self.padded_rows += bucket - n_real
            self._batch_lat.add(latency_ms)
        if profiler.profiling_active():
            self._c_batch_ms.set_value(latency_ms)

    def on_result(self, status, latency_ms=None):
        from .server import OK, TIMEOUT, ERROR, UNAVAILABLE
        if status == UNAVAILABLE:
            self.on_unavailable()
            with self._lock:
                if latency_ms is not None:
                    self._req_lat.add(latency_ms)
            return
        with self._lock:
            if status == OK:
                self.ok += 1
            elif status == TIMEOUT:
                self.timeouts += 1
            elif status == ERROR:
                self.errors += 1
            if latency_ms is not None:
                self._req_lat.add(latency_ms)

    # -- snapshot -------------------------------------------------------
    def snapshot(self):
        with self._lock:
            rows = self.batched_requests + self.padded_rows
            return {
                "requests": self.requests,
                "ok": self.ok,
                "timeouts": self.timeouts,
                "shed": self.shed,
                "invalid": self.invalid,
                "errors": self.errors,
                "unavailable": self.unavailable,
                "unavailable_rejected": self.unavailable_rejected,
                "retries": self.retries,
                "batches": self.batches,
                "batched_requests": self.batched_requests,
                "avg_batch": (self.batched_requests / self.batches
                              if self.batches else 0.0),
                "pad_waste": (self.padded_rows / rows if rows else 0.0),
                "queue_depth": self.queue_depth,
                "latency_ms": self._req_lat.percentiles(),
                "batch_latency_ms": self._batch_lat.percentiles(),
            }
