"""Zero-downtime continuous deployment: generation-fenced live weight swap.

The :class:`DeploymentController` closes the train->serve loop the north
star leaves open: a trainer keeps publishing manifest-committed checkpoints
(``model.do_checkpoint`` + the PR-5 manifest), and a live
:class:`~mxnet_tpu.serving.fleet.FleetRouter` picks each one up WITHOUT
dropping a stream, recompiling in steady state, or ever serving a torn mix
of weight generations.

The swap protocol (four phases, each a named fault point so mxstress can
kill the controller anywhere — see docs/ROBUSTNESS.md "Rolling
deployment")::

    resolve   latest_complete_checkpoint() names the target epoch; the
              manifest hash-check is the torn-checkpoint gate — a crashed
              or in-progress save is simply not a candidate.
    warmup    one new-generation copy per (name, replica) builds, loads
              and warms OUTSIDE the router lock while the old generation
              keeps serving.  Warmup pre-compiles the full bucket menu,
              so the swap adds zero steady-state recompiles (the bench
              gate asserts via ``cache_stats()``).
    cutover   fence_swap(): every staged replica's lease generation bumps
              (kvstore MembershipTable).  In-flight streams keep their
              per-stream owner tokens and keep emitting on the old
              copies; the old generation just lost the power to re-own
              or import anything new.
    commit    commit_swap(): ONE atomic routing flip under the router
              lock — no server/engine call, no fault point inside.  A
              kill anywhere before it leaves the fleet entirely on the
              old generation; after it, entirely on the new one.

After commit the controller canaries the fleet for ``canary_s``: health
off HEALTHY or an ``slo_probe`` complaint triggers ``rollback_swap`` (the
flip runs backwards; old copies were never torn down) and the bad
generation retires instead.  Otherwise ``retire_swap`` drains the old
copies — their still-running streams fenced-handoff onto one surviving
old-generation sink, so every stream finishes against the single weight
generation it started on (docs/CONCURRENCY.md invariant 13).

Controller deploys serialize on one lock: a generation published mid-swap
queues behind the running swap, it never interleaves.

    controller = deploy.DeploymentController(
        router, "/ckpt/run", engines={"chat": build_engine})
    controller.start()          # background watcher; or poll() manually
"""
from __future__ import annotations

import threading
import time

from .. import faults
from .. import profiler
from ..base import MXNetError
from ..model import latest_complete_checkpoint, load_checkpoint
from .health import HEALTHY

__all__ = ["DeploymentController"]


class DeploymentController:
    """Watches a checkpoint prefix and rolls each newly complete epoch
    across a live fleet with generation fencing and health-gated rollback.

    Parameters
    ----------
    router : FleetRouter
        The live fleet.  The controller only uses the public swap API
        (begin/stage/fence/commit/rollback/abort/retire).
    prefix : str
        Checkpoint prefix the trainer publishes under (the
        ``do_checkpoint`` prefix; completeness comes from the manifest).
    engines : dict, optional
        ``{fleet_name: build}`` for decode engines, where
        ``build(srv_name, arg_params, aux_params, generation)`` returns a
        WARMED :class:`~mxnet_tpu.serving.decode.engine.DecodeEngine`
        named ``srv_name`` carrying the new generation's weights.
    models : dict, optional
        ``{fleet_name: build}`` for batch models, where
        ``build(arg_params, aux_params, generation)`` returns a block;
        the router loads + warms it under the fleet spec's kwargs.
    allow_unverified : bool
        Passed to :func:`latest_complete_checkpoint` — opt into legacy
        prefixes with no manifest (best-effort parse check only).
    canary_s : float
        Post-commit observation window before the swap is final.  Health
        off HEALTHY or a truthy ``slo_probe(router)`` return anywhere in
        the window rolls the fleet back to the previous generation.
    slo_probe : callable, optional
        ``slo_probe(router) -> falsy | reason-string``; called repeatedly
        during the canary window.
    """

    def __init__(self, router, prefix, engines=None, models=None,
                 allow_unverified=False, poll_interval_s=0.2,
                 canary_s=0.0, canary_interval_s=0.02, slo_probe=None,
                 retire_timeout_s=10.0):
        if not engines and not models:
            raise MXNetError("DeploymentController needs at least one "
                             "engine or model builder")
        self.router = router
        self.prefix = prefix
        self.allow_unverified = bool(allow_unverified)
        self.poll_interval_s = float(poll_interval_s)
        self.canary_s = float(canary_s)
        self.canary_interval_s = float(canary_interval_s)
        self.slo_probe = slo_probe
        self.retire_timeout_s = float(retire_timeout_s)
        self._engine_builders = dict(engines or {})
        self._model_builders = dict(models or {})
        # one swap at a time: a generation published mid-swap waits here
        # (queued), it never interleaves with the running swap
        self._swap_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._rollbacks = 0
        self._deploys = 0
        self._history = []
        self._last_error = None
        self._stop = threading.Event()
        self._thread = None
        # a fresh controller (e.g. restarted after a crash) inherits the
        # fleet's committed generation rather than assuming None
        self._generation = router.stats()["deploy"]["generation"]
        domain = profiler.Domain("serving")
        self._c_generation = domain.new_counter("deploy:generation")
        self._c_swap_ms = domain.new_counter("deploy:swap_ms")
        self._c_rollbacks = domain.new_counter("deploy:rollbacks")

    # -- watcher ----------------------------------------------------------
    def start(self):
        """Background watcher: poll() every ``poll_interval_s``."""
        with self._state_lock:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(target=self._watch,
                                            name="deploy-watcher",
                                            daemon=True)
            self._thread.start()

    def stop(self):
        with self._state_lock:
            self._stop.set()
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=30.0)

    def _watch(self):
        with self._state_lock:
            stop = self._stop
        while not stop.wait(self.poll_interval_s):
            try:
                self.poll()
            except faults.SimulatedCrash:
                raise           # chaos kill: the controller thread dies
            except MXNetError as exc:
                with self._state_lock:
                    self._last_error = str(exc)

    # -- one deployment step ----------------------------------------------
    def poll(self):
        """One step: resolve the newest complete checkpoint; deploy it if
        it is newer than what the fleet serves.  Returns the deploy
        report, or None when there is nothing new."""
        epoch = latest_complete_checkpoint(
            self.prefix, allow_unverified=self.allow_unverified)
        if epoch is None:
            return None
        with self._state_lock:
            current = self._generation
        if current is not None and epoch <= current:
            return None
        return self.deploy(epoch)

    def deploy(self, epoch):
        """Roll weight generation ``epoch`` across the fleet.

        Returns a report dict (``status`` is ``"deployed"`` or
        ``"rolled_back"``).  A :class:`~mxnet_tpu.faults.SimulatedCrash`
        at any fault point propagates — that IS the controller dying; a
        restarted controller calls :meth:`recover` and the fleet is found
        serving one consistent generation.  Any other failure before
        commit aborts the staging and re-raises; the fleet never left the
        old generation."""
        with self._swap_lock:
            return self._deploy_locked(epoch)

    def _deploy_locked(self, epoch):
        with self._state_lock:
            if self._generation is not None and epoch == self._generation:
                return None
        t0 = time.monotonic()
        faults.fault_point("deploy.resolve", prefix=self.prefix,
                           epoch=epoch)
        # torn-checkpoint gate: a manifest-complete epoch loads or the
        # deploy fails here with nothing staged and nothing changed
        _sym, arg_params, aux_params = load_checkpoint(self.prefix, epoch)
        self.router.begin_swap(epoch)
        report = {"generation": epoch, "status": None,
                  "staged_engines": [], "staged_models": [],
                  "warmup_compiles": {}, "handoffs": 0, "fenced": 0,
                  "swap_ms": None, "rollback_reason": None}
        with self._state_lock:
            report["previous"] = self._generation
        try:
            placements = self.router.stats()
            for name in sorted(self._engine_builders):
                build = self._engine_builders[name]
                placed = placements["decode_models"].get(name, {}) \
                    .get("placement", [])
                if not placed:
                    raise MXNetError("decode engine %r has no routable "
                                     "placement to swap" % (name,))
                for rid in placed:
                    faults.fault_point("deploy.warmup", name=name,
                                       rid=rid, epoch=epoch)
                    eng = self.router.stage_decode(
                        name, rid,
                        lambda srv_name, _b=build: _b(
                            srv_name, arg_params, aux_params, epoch))
                    wr = getattr(eng, "warmup_report", None) or {}
                    report["warmup_compiles"]["%s@%s" % (name, rid)] = \
                        wr.get("compiles")
                    report["staged_engines"].append((name, rid))
            for name in sorted(self._model_builders):
                build = self._model_builders[name]
                placed = placements["models"].get(name, {}) \
                    .get("placement", [])
                if not placed:
                    raise MXNetError("model %r has no routable placement "
                                     "to swap" % (name,))
                for rid in placed:
                    faults.fault_point("deploy.warmup", name=name,
                                       rid=rid, epoch=epoch)
                    block = build(arg_params, aux_params, epoch)
                    self.router.stage_model(name, rid, block)
                    report["staged_models"].append((name, rid))
            faults.fault_point("deploy.cutover", epoch=epoch)
            self.router.fence_swap()
            faults.fault_point("deploy.commit", epoch=epoch)
            self.router.commit_swap()
        except faults.SimulatedCrash:
            raise               # controller death; recover() cleans up
        except BaseException:
            self.router.abort_swap()
            raise
        # committed.  Canary window: any regression flips it back.
        reason = self._canary()
        if reason is not None:
            self.router.rollback_swap(reason)
            retired = self.router.retire_swap(
                timeout_s=self.retire_timeout_s)
            report.update(status="rolled_back", rollback_reason=reason,
                          handoffs=retired["handoffs"],
                          fenced=retired["fenced"])
            with self._state_lock:
                self._rollbacks += 1
                rollbacks = self._rollbacks
                self._history.append(report)
            if profiler.profiling_active():
                self._c_rollbacks.set_value(rollbacks)
            return report
        retired = self.router.retire_swap(timeout_s=self.retire_timeout_s)
        swap_ms = (time.monotonic() - t0) * 1e3
        report.update(status="deployed", swap_ms=swap_ms,
                      handoffs=retired["handoffs"],
                      fenced=retired["fenced"])
        with self._state_lock:
            self._generation = epoch
            self._deploys += 1
            self._history.append(report)
        if profiler.profiling_active():
            self._c_generation.set_value(epoch)
            self._c_swap_ms.set_value(swap_ms)
        return report

    def _canary(self):
        """Watch the fleet for ``canary_s`` after commit.  Returns a
        rollback reason, or None when the new generation holds."""
        deadline = time.monotonic() + self.canary_s
        while True:
            health = self.router.health()
            if health != HEALTHY:
                return "fleet health %s during canary" % (health,)
            if self.slo_probe is not None:
                verdict = self.slo_probe(self.router)
                if verdict:
                    return str(verdict)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            time.sleep(min(self.canary_interval_s, remaining))

    # -- crash recovery ----------------------------------------------------
    def recover(self):
        """Clean up after a controller that died mid-swap.

        Pre-commit death leaves a staging area: abort it (staged copies
        tear down; routing never changed).  Post-commit death leaves
        retiring old copies: retire them (the committed generation
        stands).  Either way the fleet ends on ONE consistent
        generation, and ``self._generation`` re-syncs to it."""
        aborted = self.router.abort_swap()
        retired = self.router.retire_swap(timeout_s=self.retire_timeout_s)
        generation = self.router.stats()["deploy"]["generation"]
        with self._state_lock:
            self._generation = generation
        return {"aborted_staging": aborted, "generation": generation,
                "handoffs": retired["handoffs"],
                "fenced": retired["fenced"]}

    # -- observability -----------------------------------------------------
    def stats(self):
        with self._state_lock:
            return {"generation": self._generation,
                    "deploys": self._deploys,
                    "rollbacks": self._rollbacks,
                    "last_error": self._last_error,
                    "history": list(self._history[-8:])}
