"""Native library loader: compiles src/*.cc into libmxtpu.so on first use
(g++ is baked into the image; no pybind11 — plain C ABI via ctypes).

Role: the reference keeps its runtime IO/parsing in C++ (dmlc-core recordio,
src/io/); this module provides the same native fast path for the TPU build.
Every consumer falls back to pure Python when compilation is unavailable.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_lock = threading.Lock()
_lib = None
_tried = False

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC_DIR = os.path.join(_REPO_ROOT, "src")
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libmxtpu.so")

_SOURCES = ["recordio.cc", "pipeline.cc", "im2rec.cc"]


def _build():
    os.makedirs(_BUILD_DIR, exist_ok=True)
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    newest_src = max((os.path.getmtime(s) for s in srcs if os.path.exists(s)),
                     default=0)
    fallback_marker = os.path.join(_BUILD_DIR, ".recordio_only")
    if os.path.exists(_LIB_PATH) and os.path.getmtime(_LIB_PATH) >= newest_src \
            and not os.path.exists(fallback_marker):
        return True
    base = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", _LIB_PATH]
    # full build first; without libjpeg, fall back to recordio-only so the
    # native RecordIO fast path never regresses (pipeline users get the
    # python backend instead).  The marker forces a full-build retry next
    # session — e.g. after libjpeg gets installed.
    lib_current = (os.path.exists(_LIB_PATH)
                   and os.path.getmtime(_LIB_PATH) >= newest_src)
    # jpeg-dependent sources (pipeline, im2rec) drop out of the fallback
    for attempt_srcs in (srcs, [s for s in srcs
                                if "pipeline" not in s and "im2rec" not in s]):
        full = attempt_srcs is srcs
        if not full and lib_current:
            # full build still failing (libjpeg absent) and the fallback
            # .so on disk is already up to date — don't recompile it on
            # every process start
            return True
        cmd = base + attempt_srcs + (["-ljpeg"] if full else []) + ["-lpthread"]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            if full:
                if os.path.exists(fallback_marker):
                    os.remove(fallback_marker)
            else:
                open(fallback_marker, "w").close()
            return True
        except Exception:
            continue
    return False


def get_lib():
    """Return the loaded ctypes library or None (python fallback)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        # signatures
        lib.mxtpu_recio_reader_open.restype = ctypes.c_void_p
        lib.mxtpu_recio_reader_open.argtypes = [ctypes.c_char_p]
        lib.mxtpu_recio_reader_next.restype = ctypes.c_int64
        lib.mxtpu_recio_reader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
        lib.mxtpu_recio_reader_seek.restype = ctypes.c_int64
        lib.mxtpu_recio_reader_seek.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.mxtpu_recio_reader_tell.restype = ctypes.c_int64
        lib.mxtpu_recio_reader_tell.argtypes = [ctypes.c_void_p]
        lib.mxtpu_recio_reader_reset.argtypes = [ctypes.c_void_p]
        lib.mxtpu_recio_reader_close.argtypes = [ctypes.c_void_p]
        lib.mxtpu_recio_writer_open.restype = ctypes.c_void_p
        lib.mxtpu_recio_writer_open.argtypes = [ctypes.c_char_p]
        lib.mxtpu_recio_writer_write.restype = ctypes.c_int64
        lib.mxtpu_recio_writer_write.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
        lib.mxtpu_recio_writer_tell.restype = ctypes.c_int64
        lib.mxtpu_recio_writer_tell.argtypes = [ctypes.c_void_p]
        lib.mxtpu_recio_writer_close.argtypes = [ctypes.c_void_p]
        # threaded image pipeline (src/pipeline.cc) — absent when the
        # recordio-only fallback build ran (no libjpeg on the host)
        if not hasattr(lib, "mxtpu_pipe_open"):
            _lib = lib
            return _lib
        lib.mxtpu_pipe_open.restype = ctypes.c_void_p
        lib.mxtpu_pipe_open.argtypes = [ctypes.c_char_p] + [ctypes.c_int] * 6
        lib.mxtpu_pipe_next_batch.restype = ctypes.c_int64
        lib.mxtpu_pipe_next_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float)]
        lib.mxtpu_pipe_reset.argtypes = [ctypes.c_void_p]
        lib.mxtpu_pipe_skipped.restype = ctypes.c_int64
        lib.mxtpu_pipe_skipped.argtypes = [ctypes.c_void_p]
        lib.mxtpu_pipe_read_errors.restype = ctypes.c_int64
        lib.mxtpu_pipe_read_errors.argtypes = [ctypes.c_void_p]
        lib.mxtpu_pipe_close.argtypes = [ctypes.c_void_p]
        # native im2rec packer (src/im2rec.cc; same jpeg dependency)
        if hasattr(lib, "mxtpu_im2rec"):
            lib.mxtpu_im2rec.restype = ctypes.c_int64
            lib.mxtpu_im2rec.argtypes = [ctypes.c_char_p] * 4 \
                + [ctypes.c_int] * 3
        _lib = lib
        return _lib
