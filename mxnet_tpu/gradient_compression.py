"""2-bit gradient compression with error-feedback residual.

Reference: ``src/kvstore/gradient_compression.cc:44-113`` (quantize_2bit
kernel in ``gradient_compression-inl.h``): per element,
``residual += grad``; emit +threshold if ``residual >= threshold`` (subtract
it from the residual), -threshold if ``residual <= -threshold`` (add it),
else 0 — the residual carries the quantization error into the next step.

TPU-native: the quantizer is one jitted elementwise kernel producing int8
codes in {-1, 0, +1} (2 useful bits — the reference packs 16 values/float,
we ship one int8 code/value over the collective, a 4x wire saving vs fp32).
The cross-host reduce sums CODES (cast to int32 in-graph to avoid overflow)
and multiplies by the threshold afterwards, matching the reference's
server-side sum of dequantized workers' values."""
from __future__ import annotations

import functools


class TwoBitCompression:
    """Stateless quantizer; callers keep the per-key residual."""

    def __init__(self, threshold=0.5):
        self.threshold = float(threshold)
        if self.threshold <= 0:
            raise ValueError("threshold must be greater than 0")
        self._jit_quantize = None

    def quantize(self, grad, residual):
        """(grad, residual) -> (int8 codes, new residual).  jax arrays."""
        import jax
        import jax.numpy as jnp
        if self._jit_quantize is None:
            t = self.threshold

            def q(g, r):
                acc = r + g
                codes = jnp.where(acc >= t, jnp.int8(1),
                                  jnp.where(acc <= -t, jnp.int8(-1),
                                            jnp.int8(0)))
                new_r = acc - codes.astype(acc.dtype) * t
                return codes, new_r

            self._jit_quantize = jax.jit(q)
        return self._jit_quantize(grad, residual)

    def dequantize(self, codes, dtype=None):
        """codes (possibly summed over workers) -> float gradient."""
        import jax.numpy as jnp
        return codes.astype(dtype or jnp.float32) * self.threshold


def create(compression_params):
    """Factory from the kvstore set_gradient_compression params dict."""
    params = dict(compression_params)
    ctype = params.pop("type", "2bit")
    if ctype != "2bit":
        raise ValueError("unknown gradient compression type %r" % ctype)
    return TwoBitCompression(threshold=float(params.pop("threshold", 0.5)))
