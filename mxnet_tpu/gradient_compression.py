"""2-bit gradient compression with error-feedback residual.

Reference: ``src/kvstore/gradient_compression.cc:44-113`` (quantize_2bit
kernel in ``gradient_compression-inl.h``): per element,
``residual += grad``; emit +threshold if ``residual >= threshold`` (subtract
it from the residual), -threshold if ``residual <= -threshold`` (add it),
else 0 — the residual carries the quantization error into the next step.

TPU-native: the quantizer is one jitted elementwise kernel producing int8
codes in {-1, 0, +1} (2 useful bits — the reference packs 16 values/float,
we ship one int8 code/value over the collective, a 4x wire saving vs fp32).
The cross-host reduce sums CODES (cast to int32 in-graph to avoid overflow)
and multiplies by the threshold afterwards, matching the reference's
server-side sum of dequantized workers' values."""
from __future__ import annotations

import functools
import threading


def quantize_2bit(grad, residual, threshold):
    """One error-feedback quantization step (traced; jax arrays/tracers).

    ``residual += grad``; emit ±1 int8 codes where the accumulated value
    crosses ±threshold, subtracting the emitted value from the residual.
    This single definition serves both the eager kvstore path
    (:class:`TwoBitCompression` jits it per instance) and the compiled
    2-bit wire format (parallel/zero.py traces it inside the train step)."""
    import jax.numpy as jnp
    acc = residual + grad
    codes = jnp.where(acc >= threshold, jnp.int8(1),
                      jnp.where(acc <= -threshold, jnp.int8(-1),
                                jnp.int8(0)))
    new_r = acc - codes.astype(acc.dtype) * threshold
    return codes, new_r


class ResidualStore:
    """Thread-safe per-key error-feedback residual store.

    ONE bookkeeping home for every consumer of the 2-bit codec: the dist
    kvstore's ``_compressed_allreduce`` (raw jax arrays keyed by kvstore
    key) and the compiled wire format (NDArray aux handles keyed by
    parameter name, mutated in place by CachedOp writeback).  The store is
    value-agnostic; it only guarantees that concurrent pushes (kvstore
    worker threads) and step dispatches see consistent entries."""

    def __init__(self):
        self._lock = threading.Lock()
        self._residuals = {}

    def get(self, key, default=None):
        with self._lock:
            return self._residuals.get(key, default)

    def set(self, key, value):
        with self._lock:
            self._residuals[key] = value

    def get_or_create(self, key, factory):
        """The entry for ``key``, creating it via ``factory()`` if absent."""
        with self._lock:
            value = self._residuals.get(key)
            if value is None:
                value = factory()
                self._residuals[key] = value
            return value

    def keys(self):
        with self._lock:
            return list(self._residuals)

    def clear(self):
        with self._lock:
            self._residuals.clear()

    def __len__(self):
        with self._lock:
            return len(self._residuals)

    def __contains__(self, key):
        with self._lock:
            return key in self._residuals


class TwoBitCompression:
    """Stateless quantizer; callers keep the per-key residual
    (:class:`ResidualStore`)."""

    def __init__(self, threshold=0.5):
        self.threshold = float(threshold)
        if self.threshold <= 0:
            raise ValueError("threshold must be greater than 0")
        self._jit_quantize = None

    def quantize(self, grad, residual):
        """(grad, residual) -> (int8 codes, new residual).  jax arrays."""
        import jax
        if self._jit_quantize is None:
            self._jit_quantize = jax.jit(
                functools.partial(quantize_2bit, threshold=self.threshold))
        return self._jit_quantize(grad, residual)

    def dequantize(self, codes, dtype=None):
        """codes (possibly summed over workers) -> float gradient."""
        import jax.numpy as jnp
        return codes.astype(dtype or jnp.float32) * self.threshold


def create(compression_params):
    """Factory from the kvstore set_gradient_compression params dict."""
    params = dict(compression_params)
    ctype = params.pop("type", "2bit")
    if ctype != "2bit":
        raise ValueError("unknown gradient compression type %r" % ctype)
    return TwoBitCompression(threshold=float(params.pop("threshold", 0.5)))
