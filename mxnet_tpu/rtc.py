"""Runtime kernel compilation.

Reference: python/mxnet/rtc.py ``CudaModule`` over NVRTC (src/common/rtc.cc:35-69)
— compile CUDA C at runtime and launch as kernels.

TPU-native: runtime kernels are **Pallas** functions.  ``PallasModule`` wraps a
user kernel function into a launchable with the same get_kernel/launch shape as
the reference's CudaModule, compiled by XLA on first call."""
from __future__ import annotations

from .ndarray import NDArray, _wrap


class PallasModule:
    """Wrap pallas kernels for launch on NDArrays.

    Parameters
    ----------
    kernels : dict name -> callable(*jax_arrays) -> jax array
        Each callable is typically a ``pl.pallas_call`` wrapper.
    """

    def __init__(self, kernels):
        self._kernels = dict(kernels)

    def get_kernel(self, name, signature=None):
        fn = self._kernels[name]

        class _Kernel:
            def launch(self, args, ctx=None, grid_dims=None, block_dims=None,
                       shared_mem=0):
                vals = [a._data if isinstance(a, NDArray) else a for a in args]
                out = fn(*vals)
                return _wrap(out)
        return _Kernel()


# Compatibility name: reference scripts do mx.rtc.CudaModule(source). There is
# no CUDA on TPU; raise with guidance at use.
class CudaModule:
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "CudaModule is CUDA-specific; on TPU write a Pallas kernel and wrap "
            "it with mx.rtc.PallasModule")
