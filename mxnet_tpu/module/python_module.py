"""Modules implemented directly in Python, no Symbol/executor underneath.

Reference: python/mxnet/module/python_module.py — PythonModule (a Module
whose computation is arbitrary user Python; most module APIs become no-ops
because there are no parameters by default) and PythonLossModule (a
loss-head module whose backward supplies a hand-written input gradient).

TPU-native note: user computation inside these modules runs eagerly through
``mxnet_tpu.nd`` ops, so each call is an op-level jit-cached XLA dispatch;
a custom loss that should fuse belongs in a CustomOp (operator.py) or a
HybridBlock instead.  These classes exist for API parity: pipelines that
interleave a Python metric/loss stage between symbolic modules (e.g. under
SequentialModule) port unchanged.
"""
from __future__ import annotations

import logging

from .base_module import BaseModule
from ..io.io import DataDesc
from ..base import MXNetError


class PythonModule(BaseModule):
    """A module whose forward is plain Python over NDArrays.

    Subclasses override ``forward`` (and ``backward`` when trainable).
    Parameter-less by default: ``get_params`` returns empty dicts and
    ``update`` is a no-op; override both to hold state.
    """

    def __init__(self, data_names, label_names, output_names, logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    # -- information ----------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # -- parameters (none by default) -----------------------------------
    def get_params(self):
        return {}, {}

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        self.params_initialized = True

    def update(self):
        pass

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True

    # -- binding ---------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        assert grad_req == "write", "PythonModule only supports write grad_req"
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._data_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                             for d in data_shapes]
        self._label_shapes = ([d if isinstance(d, DataDesc) else DataDesc(*d)
                               for d in label_shapes]
                              if label_shapes else None)
        self._output_shapes = self._compute_output_shapes()

    def _compute_output_shapes(self):
        """Deduce output shapes from the bound input shapes; subclasses
        must implement (there is no graph to infer from)."""
        raise NotImplementedError

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        pass

    def get_input_grads(self, merge_multi_context=True):
        raise MXNetError("PythonModule subclass must implement "
                         "get_input_grads when inputs_need_grad")

    def install_monitor(self, mon):
        pass


class PythonLossModule(PythonModule):
    """A pass-through loss head: forward keeps its input, backward emits a
    caller-supplied input gradient (``grad_func``) or the canonical
    softmax-CE convenience gradient when none is given.
    """

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names=data_names, label_names=label_names,
                         output_names=["%s_output" % name], logger=logger)
        self._name = name
        assert len(self._data_names) == 1
        assert len(self._label_names) == 1
        self._scores = None
        self._labels = None
        self._scores_grad = None
        if grad_func is not None and not callable(grad_func):
            raise TypeError("grad_func must be callable")
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        # the loss output mirrors the score input
        return [(self._output_names[0], self._data_shapes[0].shape)]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        assert merge_multi_context
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, "loss module sits at the head"
        assert self.for_training
        if self._grad_func is not None:
            grad = self._grad_func(self._scores, self._labels)
        else:
            # d/dscores of softmax cross-entropy with integer labels
            from .. import ndarray as nd
            prob = nd.softmax(self._scores)
            one_hot = nd.one_hot(self._labels.astype("int32"),
                                 int(prob.shape[-1]))
            grad = prob - one_hot
        self._scores_grad = grad

    def get_input_grads(self, merge_multi_context=True):
        assert merge_multi_context
        return [self._scores_grad]
