"""DataParallelExecutorGroup: slice batches across devices.

Reference: python/mxnet/module/executor_group.py:143 — ``decide_slices`` (:281)
splits the batch axis across contexts, ``bind_exec`` (:344) binds one executor
per device via ``_bind_ith_exec`` (:641), forward/backward scatter/gather.

TPU-native: kept for API parity and used by Module for multi-context binds.
(The pjit data-parallel path in parallel/ is the performance route — one
executor over a sharded mesh rather than N replicas; this class is the
replica-per-device fallback exactly matching reference semantics.)
"""
from __future__ import annotations

import logging
import numpy as _np

from ..io.io import DataDesc
from ..ndarray import NDArray, zeros as nd_zeros, concat as nd_concat
from ..base import MXNetError


def _split_input_slice(batch_size, work_load_list):
    """Per-device batch slices proportional to each device's workload weight
    (reference: mxnet.executor_manager._split_input_slice).

    Rounds each share, gives any remainder to the last device, and errors if
    the rounding starves a device of samples entirely."""
    total = float(sum(work_load_list))
    shares = [round(batch_size * w / total) for w in work_load_list]
    shares[-1] += batch_size - sum(shares)
    slices, start = [], 0
    for share in shares:
        stop = min(start + int(share), batch_size)
        if stop <= start:
            raise ValueError("Too many slices. Some splits are empty.")
        slices.append(slice(start, stop))
        start = stop
    return slices


def _load_general(data, targets, major_axis):
    for d_src, d_targets in zip(data, targets):
        if isinstance(d_targets, NDArray):
            d_src.copyto(d_targets)
        else:
            for slice_idx, d_dst in d_targets:
                if major_axis == 0:
                    d_src[slice_idx.start:slice_idx.stop].copyto(d_dst)
                else:
                    d_src.copyto(d_dst)


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad, shared_group=None,
                 logger=logging, fixed_param_names=None, grad_req="write",
                 state_names=None, group2ctxs=None):
        self.param_names = param_names
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload or [1] * len(contexts)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.logger = logger
        self.fixed_param_names = fixed_param_names or []
        self.state_names = state_names or []
        # model-parallel placement map; a dict applies to every device, a
        # list supplies one map per device (reference executor_group.py)
        if isinstance(group2ctxs, dict):
            group2ctxs = [group2ctxs] * len(contexts)
        if group2ctxs and len(group2ctxs) != len(contexts):
            raise ValueError("group2ctxs must supply one map per context "
                             "(%d maps for %d contexts)"
                             % (len(group2ctxs), len(contexts)))
        self.group2ctxs = group2ctxs or [None] * len(contexts)
        self.grad_req = {}
        for name in self.arg_names:
            if name in self.param_names:
                self.grad_req[name] = ("null" if name in self.fixed_param_names
                                       else grad_req) if for_training else "null"
            elif name in [d[0] for d in data_shapes]:
                self.grad_req[name] = grad_req if inputs_need_grad else "null"
            else:
                self.grad_req[name] = "null"
        self.execs = []
        self.data_shapes = None
        self.label_shapes = None
        self.data_layouts = None
        self.label_layouts = None
        self.batch_size = None
        self.slices = None
        self.bind_exec(data_shapes, label_shapes, shared_group)

    def decide_slices(self, data_shapes):
        """Reference executor_group.py:281."""
        assert len(data_shapes) > 0
        major_axis = [DataDesc.get_batch_axis(getattr(ds, "layout", "NCHW"))
                      for ds in data_shapes]
        for (name, shape), axis in zip([(d.name, d.shape) for d in data_shapes],
                                       major_axis):
            if axis == -1:
                continue
            batch_size = shape[axis]
            if self.batch_size is not None:
                assert batch_size == self.batch_size
            else:
                self.batch_size = batch_size
                self.slices = _split_input_slice(self.batch_size, self.workload)
        return major_axis

    def bind_exec(self, data_shapes, label_shapes, shared_group=None,
                  reshape=False):
        self.data_layouts = self.decide_slices(data_shapes)
        if label_shapes is not None and len(label_shapes) > 0:
            self.label_layouts = self.decide_slices(label_shapes)
        self.data_shapes = data_shapes
        self.label_shapes = label_shapes
        self.execs = []
        for i in range(len(self.contexts)):
            self.execs.append(self._bind_ith_exec(i, data_shapes, label_shapes,
                                                  shared_group))
        self._collect_arrays()

    def _sliced_shape(self, shapes, i, major_axis):
        sliced = []
        for (desc, axis) in zip(shapes, major_axis):
            shape = list(desc.shape)
            if axis >= 0:
                shape[axis] = self.slices[i].stop - self.slices[i].start
            sliced.append(DataDesc(desc.name, tuple(shape),
                                   getattr(desc, "dtype", _np.float32),
                                   getattr(desc, "layout", "NCHW")))
        return sliced

    def _bind_ith_exec(self, i, data_shapes, label_shapes, shared_group):
        """Reference executor_group.py:641."""
        from ..executor import Executor
        shapes = dict()
        data_shapes_i = self._sliced_shape(data_shapes, i, self.data_layouts)
        for desc in data_shapes_i:
            shapes[desc.name] = desc.shape
        if label_shapes is not None:
            label_shapes_i = self._sliced_shape(label_shapes, i, self.label_layouts)
            for desc in label_shapes_i:
                shapes[desc.name] = desc.shape
        ctx = self.contexts[i]
        arg_shapes, _, aux_shapes = self.symbol._infer_shape_impl(False, **shapes)
        if arg_shapes is None:
            raise MXNetError("shape inference failed in bind")
        args = {}
        args_grad = {}
        for name, shape in zip(self.arg_names, arg_shapes):
            args[name] = nd_zeros(shape, ctx=ctx)
            if self.grad_req.get(name, "null") != "null":
                args_grad[name] = nd_zeros(shape, ctx=ctx)
        aux = {name: nd_zeros(shape, ctx=ctx)
               for name, shape in zip(self.aux_names, aux_shapes)}
        return Executor(self.symbol, ctx, args, args_grad, self.grad_req, aux,
                        group2ctx=self.group2ctxs[i])

    def _collect_arrays(self):
        self.data_arrays = [[(self.slices[i], e.arg_dict[name])
                             for i, e in enumerate(self.execs)]
                            for name, _ in [(d.name, d.shape) for d in self.data_shapes]]
        if self.label_shapes is not None:
            self.label_arrays = [[(self.slices[i], e.arg_dict[name])
                                  for i, e in enumerate(self.execs)]
                                 for name, _ in [(l.name, l.shape) for l in self.label_shapes]]
        else:
            self.label_arrays = None
        self.param_arrays = [[exec_.arg_dict[name] for exec_ in self.execs]
                             for name in self.param_names
                             if name in self.arg_names]
        if self.for_training:
            self.grad_arrays = [[exec_.grad_dict.get(name) for exec_ in self.execs]
                                for name in self.param_names]
        else:
            self.grad_arrays = []
        data_names = [x.name for x in self.data_shapes]
        if self.inputs_need_grad:
            self.input_grad_arrays = [[exec_.grad_dict.get(name)
                                       for exec_ in self.execs]
                                      for name in data_names]
        else:
            self.input_grad_arrays = []
        self.aux_arrays = [[exec_.aux_dict[name] for exec_ in self.execs]
                           for name in self.aux_names]

    def single_executor(self):
        """The one executor of a single-context bind.

        Whole-program capture (module/compiled_step.py) compiles forward +
        backward + update over ONE executor's arg/aux handles; the
        replica-per-device layout of a multi-context bind has no single
        set of handles to capture, so it raises instead."""
        if len(self.execs) != 1:
            raise MXNetError(
                "single_executor(): bound over %d contexts; whole-program "
                "capture needs a single-device bind (use parallel/ for the "
                "sharded path)" % len(self.execs))
        return self.execs[0]

    def set_params(self, arg_params, aux_params, allow_extra=False):
        for exec_ in self.execs:
            exec_.copy_params_from(arg_params, aux_params,
                                   allow_extra_params=allow_extra)

    def get_params(self, arg_params, aux_params):
        for name, block in zip(self.param_names, self.param_arrays):
            weight = block[0]
            if len(block) > 1:
                acc = block[0].copy()
                for w in block[1:]:
                    acc += w.as_in_context(acc.context)
                weight = acc / len(block)
            weight.astype(str(arg_params[name].dtype)).copyto(arg_params[name])
        for name, block in zip(self.aux_names, self.aux_arrays):
            weight = block[0]
            if len(block) > 1:
                acc = block[0].copy()
                for w in block[1:]:
                    acc += w.as_in_context(acc.context)
                weight = acc / len(block)
            weight.astype(str(aux_params[name].dtype)).copyto(aux_params[name])

    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        # scatter data
        import jax

        def scatter(src, name, i, e):
            # the slice must land on executor i's device — a raw buffer
            # handoff would leave it on the source device and jit would
            # reject the mixed placement
            sl = self.slices[i]
            val = src[sl.start:sl.stop]._data if len(self.execs) > 1 \
                else src._data
            if len(self.contexts) > 1:
                val = jax.device_put(val, self.contexts[i].jax_device())
            e.arg_dict[name]._set_data(val)

        for j, desc in enumerate(self.data_shapes):
            for i, e in enumerate(self.execs):
                scatter(data_batch.data[j], desc.name, i, e)
        if self.label_shapes is not None and data_batch.label:
            for j, desc in enumerate(self.label_shapes):
                for i, e in enumerate(self.execs):
                    scatter(data_batch.label[j], desc.name, i, e)
        for e in self.execs:
            e.forward(is_train=is_train)

    def backward(self, out_grads=None):
        assert self.for_training, "re-bind with for_training=True to run backward"
        for i, e in enumerate(self.execs):
            out_grads_slice = None
            if out_grads is not None:
                out_grads_slice = []
                for grad in out_grads:
                    if len(self.execs) > 1:
                        sl = self.slices[i]
                        out_grads_slice.append(grad[sl.start:sl.stop]
                                               .as_in_context(self.contexts[i]))
                    else:
                        out_grads_slice.append(grad)
            e.backward(out_grads_slice)

    def get_outputs(self, merge_multi_context=True):
        outputs = [[e.outputs[i] for e in self.execs]
                   for i in range(len(self.execs[0].outputs))]
        if merge_multi_context:
            merged = []
            for per_dev in outputs:
                if len(per_dev) == 1:
                    merged.append(per_dev[0])
                else:
                    merged.append(nd_concat(*[o.as_in_context(per_dev[0].context)
                                              for o in per_dev], dim=0))
            return merged
        return outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        if merge_multi_context:
            merged = []
            for per_dev in self.input_grad_arrays:
                if len(per_dev) == 1:
                    merged.append(per_dev[0])
                else:
                    merged.append(nd_concat(*per_dev, dim=0))
            return merged
        return self.input_grad_arrays

    def get_states(self, merge_multi_context=True):
        return []

    def set_states(self, states=None, value=None):
        pass

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        for current_exec, (texec, islice) in enumerate(zip(self.execs, self.slices)):
            if not pre_sliced:
                labels_slice = []
                for label in labels:
                    if len(self.execs) > 1:
                        labels_slice.append(label[islice.start:islice.stop])
                    else:
                        labels_slice.append(label)
            else:
                labels_slice = labels[current_exec]
            eval_metric.update(labels_slice, texec.outputs)

    def install_monitor(self, mon):
        for e in self.execs:
            e.set_monitor_callback(mon.stat_helper if hasattr(mon, "stat_helper")
                                   else mon)
