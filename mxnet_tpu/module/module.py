"""Module: symbolic training on one or more devices.

Reference: python/mxnet/module/module.py — bind (:474) creates the
DataParallelExecutorGroup, init_params/init_optimizer (:666), forward/backward,
update (:644) choosing update_on_kvstore vs local updater, save/load_checkpoint
with optimizer state (:165).
"""
from __future__ import annotations

import logging
import warnings

from .base_module import BaseModule, _check_input_names
from .executor_group import DataParallelExecutorGroup
from .. import ndarray as nd
from .. import optimizer as opt
from ..context import cpu, Context
from ..initializer import Uniform, InitDesc
from ..model import (_create_kvstore, _initialize_kvstore, _update_params,
                     _update_params_on_kvstore, _update_params_on_kvstore_nccl,
                     load_checkpoint)
from ..io.io import DataDesc
from ..ndarray import zeros


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        ctxs = context if context is not None else cpu()
        self._context = [ctxs] if isinstance(ctxs, Context) else list(ctxs)
        self._work_load_list = (list(work_load_list) if work_load_list
                                else [1] * len(self._context))
        if len(self._work_load_list) != len(self._context):
            raise AssertionError("work_load_list must have one entry per context")

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []

        arg_names = symbol.list_arguments()
        input_names = data_names + label_names + (state_names or [])
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = list(fixed_param_names or [])
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = list(state_names or [])
        self._output_names = symbol.list_outputs()

        _check_input_names(symbol, data_names, "data", True)
        _check_input_names(symbol, label_names, "label", False)
        _check_input_names(symbol, self._state_names, "state", True)
        _check_input_names(symbol, self._fixed_param_names, "fixed_param", True)

        # parameter state, optimizer state, and bind state all start empty
        for attr in ("_arg_params", "_aux_params", "_optimizer", "_kvstore",
                     "_update_on_kvstore", "_updater", "_preload_opt_states",
                     "_grad_req", "_exec_group", "_data_shapes", "_label_shapes"):
            setattr(self, attr, None)
        self._params_dirty = False
        self._compression_params = compression_params
        self._group2ctxs = group2ctxs

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        remove_amp_cast=True):
        """Crash-consistent checkpoint: symbol + params (+ optimizer states)
        are each written atomically, then committed together as one entry in
        ``prefix-manifest.json`` — a crash anywhere leaves the previous
        complete checkpoint restorable (docs/ROBUSTNESS.md)."""
        from ..model import record_checkpoint
        symbol_file = "%s-symbol.json" % prefix
        self._symbol.save(symbol_file)
        param_name = "%s-%04d.params" % (prefix, epoch)
        self.save_params(param_name)
        files = [symbol_file, param_name]
        logging.info("Saved checkpoint to \"%s\"", param_name)
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name)
            files.append(state_name)
            logging.info("Saved optimizer state to \"%s\"", state_name)
        record_checkpoint(prefix, epoch, files)

    def _reset_bind(self):
        self.binded = False
        self._exec_group = self._data_shapes = self._label_shapes = None

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        outputs = self._exec_group.execs[0].forward(is_train=False) \
            if not self._exec_group.execs[0].outputs else self._exec_group.execs[0].outputs
        return list(zip(self._output_names, [o.shape for o in outputs]))

    def get_params(self):
        assert self.binded and self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False. "
                          "init_params call ignored.", stacklevel=2)
            return
        assert self.binded, "call bind before initializing the parameters"
        if initializer is None:
            initializer = Uniform(0.01)

        if self._arg_params is None:
            param_arrays = [zeros(x[0].shape, dtype=str(x[0].dtype))
                            for x in self._exec_group.param_arrays]
            self._arg_params = {name: arr for name, arr in
                                zip(self._param_names, param_arrays)}
        if self._aux_params is None:
            aux_arrays = [zeros(x[0].shape, dtype=str(x[0].dtype))
                          for x in self._exec_group.aux_arrays]
            self._aux_params = {name: arr for name, arr in
                                zip(self._aux_names, aux_arrays)}

        def _fill(desc, arr, provided):
            # prefer a user-provided value; otherwise fall back to the
            # initializer (or fail, when missing values are not allowed)
            src = provided.get(desc) if provided is not None else None
            if src is not None:
                if src is not arr:
                    src.copyto(arr)
                return
            if provided is not None and not allow_missing:
                raise RuntimeError("%s is not presented" % desc)
            if initializer is not None:
                initializer(desc, arr)

        attrs = self._symbol.attr_dict()
        for params, provided in ((self._arg_params, arg_params),
                                 (self._aux_params, aux_params)):
            for name, arr in sorted(params.items()):
                _fill(InitDesc(name, attrs.get(name, None)), arr, provided)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params,
                                    allow_extra=allow_extra)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params, allow_missing=allow_missing,
                             force_init=force_init, allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False. "
                          "set_params call ignored.", stacklevel=2)
            return
        self._exec_group.set_params(arg_params, aux_params, allow_extra=allow_extra)
        self._params_dirty = True
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        if not for_training:
            assert not inputs_need_grad

        self._data_shapes, self._label_shapes = _parse_data_desc(
            self.data_names, self.label_names, data_shapes, label_shapes)

        shared_group = None
        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list,
            self._data_shapes, self._label_shapes, self._param_names,
            for_training, inputs_need_grad, shared_group, logger=self.logger,
            fixed_param_names=self._fixed_param_names, grad_req=grad_req,
            state_names=self._state_names, group2ctxs=self._group2ctxs)
        self.binded = True

        if self.params_initialized:
            # params were set before binding (e.g. Module.load)
            self._exec_group.set_params(self._arg_params, self._aux_params)

        if shared_module is not None and shared_module.params_initialized:
            self.set_params(*shared_module.get_params())

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        self._data_shapes, self._label_shapes = _parse_data_desc(
            self.data_names, self.label_names, data_shapes, label_shapes)
        self._exec_group.bind_exec(self._data_shapes, self._label_shapes,
                                   reshape=True)
        if self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        if self._params_dirty:
            self._sync_params_from_devices()

        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params)
        batch_size = self._exec_group.batch_size
        if kvstore and "dist" in kvstore.type and "_async" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        idx2name = {}
        if update_on_kvstore:
            idx2name.update(enumerate(self._exec_group.param_names))
        else:
            for k in range(len(self._context)):
                idx2name.update({i * len(self._context) + k: n
                                 for i, n in enumerate(self._exec_group.param_names)})

        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name, **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)
            if optimizer.rescale_grad != rescale_grad:
                warnings.warn("Optimizer created manually outside Module but "
                              "rescale_grad is not normalized to 1.0/batch_size/"
                              "num_workers (%s vs. %s)."
                              % (optimizer.rescale_grad, rescale_grad))

        self._optimizer, self._kvstore = optimizer, kvstore
        self._update_on_kvstore = update_on_kvstore
        # either the kvstore applies updates (set_optimizer) or we keep a
        # local updater; never both
        self._updater = None if update_on_kvstore else opt.get_updater(optimizer)

        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            _initialize_kvstore(kvstore=kvstore,
                                param_arrays=self._exec_group.param_arrays,
                                arg_params=self._arg_params,
                                param_names=self._param_names,
                                update_on_kvstore=update_on_kvstore)
        if update_on_kvstore:
            kvstore.set_optimizer(self._optimizer)

        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def borrow_optimizer(self, shared_module):
        assert shared_module.optimizer_initialized
        for attr in ("_optimizer", "_kvstore", "_update_on_kvstore", "_updater"):
            setattr(self, attr, getattr(shared_module, attr))
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        curr_data_shapes = tuple(i.shape for i in self._data_shapes)
        if isinstance(data_batch, list):
            assert data_batch is not None, "Encountered empty data batch"
            new_data_shapes = tuple(i.shape for i in data_batch[0].data)
        else:
            new_data_shapes = tuple(i.shape for i in data_batch.data)
        if curr_data_shapes != new_data_shapes:
            if hasattr(data_batch, "provide_data") and data_batch.provide_data:
                new_dshape = data_batch.provide_data
            else:
                new_dshape = [DataDesc(i.name, shape, i.dtype, i.layout)
                              for i, shape in zip(self._data_shapes, new_data_shapes)]
            if hasattr(data_batch, "provide_label") and data_batch.provide_label:
                new_lshape = data_batch.provide_label
            elif hasattr(data_batch, "label") and data_batch.label:
                new_lshape = [DataDesc(i.name, j.shape, i.dtype, i.layout)
                              for i, j in zip(self._label_shapes, data_batch.label)]
            else:
                new_lshape = None
            self.reshape(new_dshape, new_lshape)
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized and self.optimizer_initialized
        self._params_dirty = True
        group = self._exec_group
        if self._update_on_kvstore:
            _update_params_on_kvstore(group.param_arrays, group.grad_arrays,
                                      self._kvstore, group.param_names)
        else:
            _update_params(group.param_arrays, group.grad_arrays,
                           updater=self._updater,
                           num_device=len(self._context),
                           kvstore=self._kvstore,
                           param_names=group.param_names)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec_group.get_outputs(merge_multi_context=merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context=merge_multi_context)

    def get_states(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec_group.get_states(merge_multi_context=merge_multi_context)

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized
        return self._exec_group.set_states(states, value)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._exec_group.update_metric(eval_metric, labels, pre_sliced)

    def _sync_params_from_devices(self):
        self._exec_group.get_params(self._arg_params, self._aux_params)
        if self._updater is not None:
            pass
        self._params_dirty = False

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            from ..util import write_atomic
            write_atomic(fname, self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    def install_monitor(self, mon):
        assert self.binded
        self._exec_group.install_monitor(mon)

    def gradient_residual_store(self):
        """The module's error-feedback residual store
        (:class:`~mxnet_tpu.gradient_compression.ResidualStore`), created
        on first use and persistent for the module's lifetime — the same
        per-key store shape the dist kvstore's ``set_gradient_compression``
        path keeps, here adopted by ``fit(wire_format="2bit")``'s compiled
        2-bit reduce so the quantization residual carries across steps AND
        across fit() calls."""
        store = getattr(self, "_residual_store", None)
        if store is None:
            from ..gradient_compression import ResidualStore
            store = ResidualStore()
            self._residual_store = store
        return store

    def _compiled_step_handles(self):
        """Everything CompiledTrainStep.from_module needs to capture this
        module's whole training iteration as one CachedOp, or raise
        CompiledStepUnsupported with the reason the eager loop must run
        (module/compiled_step.py owns the traceability checks on top)."""
        from .compiled_step import CompiledStepUnsupported
        if not (self.binded and self.params_initialized
                and self.optimizer_initialized):
            raise CompiledStepUnsupported(
                "module must be bound/initialized with an optimizer")
        if len(self._context) != 1:
            raise CompiledStepUnsupported(
                "multi-context bind (%d devices); the compiled step needs a "
                "single-device executor" % len(self._context))
        if self._kvstore is not None or self._update_on_kvstore:
            raise CompiledStepUnsupported(
                "kvstore-backed update; the compiled step needs the local "
                "updater path")
        if self._state_names:
            raise CompiledStepUnsupported(
                "state_names carry mutable module state across steps")
        if self._group2ctxs:
            raise CompiledStepUnsupported(
                "group2ctxs model parallelism pins ops to devices, which "
                "needs eager dispatch")
        if self.inputs_need_grad:
            raise CompiledStepUnsupported(
                "inputs_need_grad: input gradients are not materialized by "
                "the fused step")
        return {
            "executor": self._exec_group.single_executor(),
            "optimizer": self._optimizer,
            "updater": self._updater,
            "param_names": list(self._param_names),
            # bound-shape order, NOT self._data_names order: batch.data
            # arrives in the iterator's provide_data order, and the eager
            # scatter (executor_group.forward) matches positionally against
            # data_shapes — the compiled step must bind the same way or a
            # provide order differing from data_names order would silently
            # swap same-shaped inputs
            "data_names": [d.name for d in self._data_shapes],
            "label_names": [l.name for l in (self._label_shapes or [])],
            "context": self._context[0],
            "residual_store": self.gradient_residual_store,
        }

    def prepare(self, data_batch, sparse_row_id_fn=None):
        assert self.binded


def _parse_data_desc(data_names, label_names, data_shapes, label_shapes):
    data_shapes = [x if isinstance(x, DataDesc) else DataDesc(*x)
                   for x in data_shapes]
    _check_names_match(data_names, data_shapes, "data", True)
    if label_shapes is not None:
        label_shapes = [x if isinstance(x, DataDesc) else DataDesc(*x)
                        for x in label_shapes]
        _check_names_match(label_names, label_shapes, "label", False)
    else:
        _check_names_match(label_names, [], "label", False)
    return data_shapes, label_shapes


def _check_names_match(data_names, data_shapes, name, throw):
    actual = [x[0] for x in data_shapes]
    if sorted(data_names) != sorted(actual):
        msg = "Data provided by %s_shapes don't match names specified by " \
              "%s_names (%s vs. %s)" % (name, name, str(data_shapes), str(data_names))
        if throw:
            raise ValueError(msg)
        warnings.warn(msg)
