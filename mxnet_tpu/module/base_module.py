"""BaseModule: the high-level symbolic training loop.

Reference: python/mxnet/module/base_module.py — ``fit`` (:410) drives
bind → init_params → init_optimizer → per-batch forward_backward/update/
update_metric with epoch callbacks; score/predict evaluation entry points.
"""
from __future__ import annotations

import logging
import time

from .. import metric as metric_mod
from ..model import BatchEndParam
from ..base import string_types
from ..ndarray import NDArray
from ..context import cpu


def _as_list(obj):
    if isinstance(obj, list):
        return obj
    return [obj]


def _fire(callbacks, *args):
    """Invoke a callback, a list of callbacks, or nothing (None)."""
    if callbacks is None:
        return
    for callback in _as_list(callbacks):
        callback(*args)


_NO_BATCH = object()  # sentinel: iterator exhausted


def _check_input_names(symbol, names, typename, throw):
    args = symbol.list_arguments()
    for name in names:
        if name in args:
            continue
        candidates = [arg for arg in args if arg not in names]
        msg = "\033[91mYou created Module with Module(..., %s_names=%s) but input with" \
              " name '%s' is not found in symbol.list_arguments(). Did you mean one" \
              " of:\n\t%s\033[0m" % (typename, str(names), name, "\n\t".join(candidates))
        if throw:
            raise ValueError(msg)
        logging.warning(msg)


class BaseModule:
    # lifecycle flags, all False until the corresponding stage runs
    _STAGE_FLAGS = ("binded", "for_training", "inputs_need_grad",
                    "params_initialized", "optimizer_initialized")

    def __init__(self, logger=logging):
        self.logger = logger
        for flag in self._STAGE_FLAGS:
            setattr(self, flag, False)
        self._symbol = None
        self._total_exec_bytes = 0

    # ------------------------------------------------------------------
    # high-level
    # ------------------------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None, batch_end_callback=None,
              score_end_callback=None, reset=True, epoch=0, sparse_row_id_fn=None):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self._metric_from_batch(eval_metric, eval_batch)
            _fire(batch_end_callback,
                  BatchEndParam(epoch=epoch, nbatch=nbatch,
                                eval_metric=eval_metric, locals=locals()))
            actual_num_batch += 1
        if score_end_callback:
            _fire(score_end_callback,
                  BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                eval_metric=eval_metric, locals=locals()))
        return eval_metric.get_name_value()

    def _metric_from_batch(self, eval_metric, batch):
        """Update a metric from one batch, which may be pre-sliced per device."""
        if isinstance(batch, list):
            self.update_metric(eval_metric, [b.label for b in batch],
                               pre_sliced=True)
        else:
            self.update_metric(eval_metric, batch.label)

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad] for out in self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True, reset=True,
                always_output_list=False, sparse_row_id_fn=None):
        from .. import ndarray as nd
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad].copy() for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                assert len(out) == num_outputs
            output_list2 = [nd.concat(*[out[i] for out in output_list], dim=0)
                            for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None, monitor=None,
            sparse_row_id_fn=None, prefetch_to_device=None,
            resume_from=None, auto_resume=False, compiled=None,
            steps_per_call=1, metric_interval=None, donate="auto",
            shard_update=False, wire_format=None, wire_threshold=0.5):
        """Train the module (reference base_module.py:410).

        Compiled training (default ON, docs/PERF.md "Compiled training
        step"): ``compiled=None``/``True`` captures forward + backward +
        optimizer update as ONE CachedOp via
        :class:`~mxnet_tpu.module.compiled_step.CompiledTrainStep` —
        params/optimizer state update in place on device, metrics accumulate
        on-device, and the host fetches them only every ``metric_interval``
        batches (``None`` = at epoch end only), so the per-batch host
        barrier of the eager loop is gone.  ``steps_per_call=N`` scans a
        window of N batches per dispatch.  Configurations the capture cannot
        express (multi-context binds, kvstore updates, non-trace_safe
        optimizers, metrics with no device twin, monitors) fall back to the
        eager loop with a one-line warning; ``compiled=False`` forces eager.
        Under the compiled path, callbacks observe metric values that lag by
        up to ``metric_interval`` batches.

        ``shard_update=True`` (docs/PERF.md "Sharded weight update (ZeRO)")
        runs the compiled step's optimizer update ZeRO-sharded over all
        local devices: optimizer state lives dp-sharded at 1/N bytes per
        replica and each replica updates only its flat parameter shard
        (bitwise-equal to the replicated step for elementwise optimizers;
        checkpoints/resume keep working — the updater's state arrays simply
        hold the flat sharded form).  ``wire_format="2bit"`` additionally
        routes the gradient reduce through the error-feedback 2-bit codec
        (``wire_threshold`` is its quantization step) — 4x fewer wire
        bytes, with the residual carried per replica in the module's shared
        ResidualStore.  Both require the compiled path: configurations that
        fall back to eager train replicated, with the usual warning.

        ``prefetch_to_device`` (a Context) routes each epoch's batches
        through an ``io.DeviceFeed``: a background thread stays up to two
        batches ahead, staging DataBatch arrays onto the device so the
        step never pays decode or host→device transfer inline (safe even
        for iterators that reuse host buffers between ``next()`` calls —
        staging copies each batch to the device before the feed advances
        the source again).

        Crash recovery (docs/ROBUSTNESS.md): ``resume_from=prefix`` scans
        ``prefix-manifest.json`` for the newest COMPLETE checkpoint (torn
        or uncommitted saves are skipped by content hash), restores params
        + optimizer state + epoch, and continues training from there; with
        no complete checkpoint it raises.  ``auto_resume=True`` is the
        opportunistic form: resume when a complete checkpoint exists, start
        fresh otherwise — and when ``resume_from`` is not given, the prefix
        is discovered from a ``do_checkpoint``/``module_checkpoint`` epoch
        callback (their ``checkpoint_prefix`` attribute), so the idiom
        ``fit(..., epoch_end_callback=do_checkpoint(p), auto_resume=True)``
        makes a preempted-and-restarted job pick itself back up.
        """
        assert num_epoch is not None, "please specify number of epochs"
        import os
        from ..initializer import Uniform
        if initializer is None:
            initializer = Uniform(0.01)

        resume_prefix = resume_from
        if resume_prefix is None and auto_resume and \
                epoch_end_callback is not None:
            for cb in _as_list(epoch_end_callback):
                prefix = getattr(cb, "checkpoint_prefix", None)
                if prefix:
                    resume_prefix = prefix
                    break
        resume_epoch = None
        if resume_prefix is not None:
            from ..model import latest_complete_checkpoint, load_checkpoint
            resume_epoch = latest_complete_checkpoint(resume_prefix)
            if resume_epoch is None:
                if not auto_resume:
                    raise FileNotFoundError(
                        "resume_from=%r: no complete checkpoint found "
                        "(torn/partial saves are skipped via the manifest)"
                        % resume_prefix)
                self.logger.info("auto_resume: no complete checkpoint under "
                                 "%r; starting fresh", resume_prefix)
            else:
                _, arg_params, aux_params = load_checkpoint(resume_prefix,
                                                            resume_epoch)
                force_init = True
                allow_missing = False
                begin_epoch = max(begin_epoch, resume_epoch)
                self.logger.info("Resuming from checkpoint %r epoch %d",
                                 resume_prefix, resume_epoch)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        if resume_epoch is not None:
            # optimizer state rides along only when the manifest committed
            # it for that epoch — a stray .states from a torn save is not
            # trusted (checkpoint_files returns only hash-verified entries)
            from ..model import checkpoint_files
            state_file = "%s-%04d.states" % (resume_prefix, resume_epoch)
            listed = checkpoint_files(resume_prefix, resume_epoch)
            if listed is not None and state_file in listed and \
                    os.path.exists(state_file) and \
                    hasattr(self, "load_optimizer_states"):
                self.load_optimizer_states(state_file)
                self.logger.info("Restored optimizer state from %r",
                                 state_file)

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        if (shard_update or wire_format is not None) and compiled is not None \
                and not compiled:
            raise ValueError("shard_update/wire_format need the compiled "
                             "path (fit(compiled=False) trains replicated)")
        compiled_step = None
        if compiled is None or compiled:
            from .compiled_step import (CompiledTrainStep,
                                        CompiledStepUnsupported)
            reason = None
            if monitor is not None:
                reason = "a monitor needs per-op eager dispatch"
            elif sparse_row_id_fn is not None:
                reason = "sparse_row_id_fn prefetch is an eager-loop hook"
            else:
                try:
                    compiled_step = CompiledTrainStep.from_module(
                        self, eval_metric=eval_metric,
                        steps_per_call=steps_per_call, donate=donate,
                        shard_update=shard_update, wire_format=wire_format,
                        wire_threshold=wire_threshold)
                except CompiledStepUnsupported as exc:
                    reason = str(exc)
            if compiled_step is None:
                if shard_update or wire_format is not None:
                    self.logger.warning(
                        "fit(shard_update=%s, wire_format=%s): the ZeRO "
                        "sharded update is unavailable here — training "
                        "REPLICATED via the eager loop: %s",
                        shard_update, wire_format, reason)
                else:
                    self.logger.warning(
                        "fit(compiled=%s): falling back to the eager loop: "
                        "%s", compiled, reason)
        self._compiled_step = compiled_step

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            eval_name_vals = []
            feed = None
            if prefetch_to_device is not None:
                from ..io.device_feed import DeviceFeed
                feed = DeviceFeed(train_data, ctx=prefetch_to_device,
                                  name="fit")
                batches = iter(feed)
            else:
                batches = iter(train_data)
            try:
                if compiled_step is not None:
                    nbatch, eval_name_vals = self._fit_compiled_epoch(
                        compiled_step, batches, eval_metric, epoch,
                        batch_end_callback, metric_interval)
                    data_batch = _NO_BATCH
                else:
                    data_batch = next(batches, _NO_BATCH)
                    nbatch = 0
                while data_batch is not _NO_BATCH:
                    if monitor is not None:
                        monitor.tic()
                    self.forward_backward(data_batch)
                    self.update()
                    self._metric_from_batch(eval_metric, data_batch)
                    # only fetch the next batch AFTER training on this one —
                    # a DataIter may reuse the previous batch's buffers on
                    # next() (the feed path is exempt: batches arrive as
                    # device copies, staged before the source advances)
                    upcoming = next(batches, _NO_BATCH)
                    if upcoming is not _NO_BATCH:
                        # prefetch hook for the next batch (sparse row pull)
                        self.prepare(upcoming,
                                     sparse_row_id_fn=sparse_row_id_fn)
                    if monitor is not None:
                        monitor.toc_print()
                    if upcoming is _NO_BATCH:
                        # snapshot before callbacks may auto-reset the metric
                        eval_name_vals = eval_metric.get_name_value()
                    _fire(batch_end_callback,
                          BatchEndParam(epoch=epoch, nbatch=nbatch,
                                        eval_metric=eval_metric,
                                        locals=locals()))
                    data_batch = upcoming
                    nbatch += 1
            finally:
                if feed is not None:
                    feed.close()
            for name, val in eval_name_vals:
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            toc = time.time()
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, (toc - tic))
            arg_params_, aux_params_ = self.get_params()
            if compiled_step is None:
                # multi-device sync-back: each replica gets the averaged
                # params.  The compiled path is single-device and its state
                # handles ARE the canonical buffers — writing the same
                # values back would only swap committed jit-output buffers
                # for fresh copies and silently flip the step's jit cache
                # key (one stealth recompile per epoch).
                self.set_params(arg_params_, aux_params_)
            _fire(epoch_end_callback, epoch, self.symbol, arg_params_, aux_params_)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch, name, val)
            train_data.reset()

    def _fit_compiled_epoch(self, cstep, batches, eval_metric, epoch,
                            batch_end_callback, metric_interval):
        """One epoch through the compiled train step (docs/PERF.md).

        Batches group into windows of ``cstep.steps_per_call`` (the epoch
        tail dispatches as a shorter window — one extra compiled signature,
        stable across epochs); each window is ONE CachedOp dispatch with no
        host fetch.  Metrics sync from the device accumulators only every
        ``metric_interval`` batches and at epoch end, so callbacks observe
        values that lag up to one interval."""
        nbatch = 0
        eval_name_vals = []
        window = []
        data_batch = next(batches, _NO_BATCH)
        while data_batch is not _NO_BATCH:
            if isinstance(data_batch, list):
                raise ValueError("pre-sliced multi-device batches reach the "
                                 "compiled path only through a bug: "
                                 "multi-context binds fall back to eager")
            window.append(data_batch)
            upcoming = next(batches, _NO_BATCH)
            if len(window) == cstep.steps_per_call or upcoming is _NO_BATCH:
                cstep.run_window([tuple(b.data) + tuple(b.label or ())
                                  for b in window])
                last_in_epoch = upcoming is _NO_BATCH
                for i in range(len(window)):
                    done = nbatch + 1
                    is_final = last_in_epoch and i == len(window) - 1
                    if is_final or (metric_interval
                                    and done % metric_interval == 0):
                        cstep.sync_metric()
                    if is_final:
                        # snapshot before callbacks may auto-reset the metric
                        eval_name_vals = eval_metric.get_name_value()
                    _fire(batch_end_callback,
                          BatchEndParam(epoch=epoch, nbatch=nbatch,
                                        eval_metric=eval_metric,
                                        locals=locals()))
                    nbatch = done
                window = []
            data_batch = upcoming
        return nbatch, eval_name_vals

    # ------------------------------------------------------------------
    # abstract interface
    # ------------------------------------------------------------------
    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    @property
    def symbol(self):
        return self._symbol

    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        from .. import ndarray as nd
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        nd.save(fname, save_dict)

    def load_params(self, fname):
        from .. import ndarray as nd
        save_dict = nd.load(fname)
        arg_params = {}
        aux_params = {}
        for k, value in save_dict.items():
            arg_type, name = k.split(":", 1)
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)

    def get_states(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        assert not merge_multi_context
        return []

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized
        assert not states and not value

    def install_monitor(self, mon):
        raise NotImplementedError()

    def prepare(self, data_batch, sparse_row_id_fn=None):
        pass

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()
