"""CompiledTrainStep: the whole training iteration as ONE CachedOp.

The eager ``fit()`` loop dispatches forward, backward, one optimizer-update
kernel per parameter, and a metric fetch — with a host sync on every batch.
The live TPU capture (BENCH_LIVE.json) shows what that costs: ResNet-50 at
MFU 0.178, the hardware ~5x underused.  This module promotes the fused step
that tools/input_bench.py proved in miniature (one XLA module per iteration,
1.56x end-to-end) to a first-class citizen of the module layer — the
"compile the whole program, not ops" thesis of the Julia->TPU paper
(arxiv 1810.09868), with the dataflow-step discipline of TensorFlow
(arxiv 1605.08695):

* forward + backward + the optimizer update of EVERY parameter are captured
  as one :class:`~mxnet_tpu.cached_op.CachedOp`; all mutable training state
  (params, BatchNorm running stats, optimizer slots, metric accumulators)
  rides as CachedOp aux and is written back in place after each dispatch;
* buffer donation (``CachedOp(flags={'donate_params': True})``) lets XLA
  alias each state input's allocation to its output — true in-place update;
  on CPU backends donation is a no-op, so ``donate='auto'`` only requests it
  off-CPU;
* ``steps_per_call=N`` wraps the step in ``jax.lax.scan`` over a
  device-resident window of N microbatches, so N optimizer steps cost ONE
  dispatch (and one host->device transfer of the stacked window);
* metrics accumulate ON DEVICE through each metric's ``traced_update`` twin
  (metric.py); the host fetches the (sum, count) scalars only at
  ``metric_interval`` boundaries or at epoch end — the per-step host
  barrier is gone;
* per-step hyperparameters (the step count ``t`` and the scheduler-resolved
  base learning rate) enter the trace as scalar INPUTS, so lr schedules and
  t-dependent optimizers (Adam bias correction, FTML) run compiled without
  per-step recompiles.

Two frontends share the machinery:

* :meth:`CompiledTrainStep.from_module` — a bound symbolic ``Module`` with
  its initialized optimizer; the step is built over the executor's traced
  graph (grads = vjp with ones cotangents, the ``backward()`` contract) and
  the optimizer's own ``update_multi_precision`` traced through NDArray
  tracer handles, so the compiled and eager paths run the SAME update
  kernels.  This is what ``BaseModule.fit(compiled=True)`` uses.
* :meth:`CompiledTrainStep.from_block` — a gluon block + explicit loss;
  used by tools/input_bench.py and bench.py so the benches and ``fit()``
  exercise one code path.

Limitations become :class:`CompiledStepUnsupported` (the caller falls back
to the eager loop with a one-line warning): multi-context binds, kvstore
updates, non-``trace_safe`` optimizers, metrics with no device twin.
"""
from __future__ import annotations

import contextlib

import numpy as _np

from .. import autograd
from ..base import MXNetError
from ..cached_op import CachedOp
from ..ndarray import NDArray, _wrap

__all__ = ["CompiledTrainStep", "CompiledStepUnsupported"]


class CompiledStepUnsupported(MXNetError):
    """This configuration cannot be captured as a single compiled step;
    the message says why.  Callers fall back to the eager loop."""


# ---------------------------------------------------------------------------
# optimizer capture helpers
# ---------------------------------------------------------------------------

_MISSING = object()


@contextlib.contextmanager
def _step_hyperparams(opt, lr_val, t_val):
    """Route the optimizer's per-step hyperparameters through traced scalars
    for the duration of one traced update.

    ``_get_lr`` returns ``lr_val`` (the host-resolved base lr for this
    microstep, scheduler already applied) times the static per-param
    multiplier, and ``_index_update_count[...]`` reads as ``t_val`` — so
    t-dependent math (Adam bias correction, FTML) stays correct across steps
    of one compiled executable.  Count WRITES are discarded: the host
    advances the real counters after the dispatch
    (CompiledTrainStep._advance_counts)."""

    class _Counts(dict):
        def __missing__(self, key):
            return t_val

        def __setitem__(self, key, value):
            pass

    saved = {name: opt.__dict__.get(name, _MISSING)
             for name in ("_get_lr", "_update_count", "_index_update_count")}
    opt._get_lr = lambda index: lr_val * opt._index_mult(
        index, opt.lr_mult, "lr_mult")
    opt._update_count = lambda index: None
    opt._index_update_count = _Counts()
    try:
        yield
    finally:
        for name, value in saved.items():
            if value is _MISSING:
                del opt.__dict__[name]
            else:
                setattr(opt, name, value)


def _state_leaf_nds(state):
    """NDArray leaves of an optimizer-state structure, depth-first."""
    if isinstance(state, NDArray):
        return [state]
    if isinstance(state, (list, tuple)):
        return [leaf for part in state for leaf in _state_leaf_nds(part)]
    return []   # None / plain scalars carry no device state


def _rebuild_state(template, leaf_iter):
    """The template structure with NDArray leaves drawn from ``leaf_iter``."""
    if isinstance(template, NDArray):
        return next(leaf_iter)
    if isinstance(template, (list, tuple)):
        return type(template)(_rebuild_state(part, leaf_iter)
                              for part in template)
    return template


def _check_optimizer(opt):
    if not getattr(opt, "trace_safe", False):
        raise CompiledStepUnsupported(
            "optimizer %s is not marked trace_safe (its update cannot be "
            "captured in a fixed trace)" % type(opt).__name__)


def _metric_leaves(metric):
    """Flatten a metric (possibly composite) into device-updatable leaves."""
    from .. import metric as metric_mod
    if metric is None:
        return []
    if isinstance(metric, metric_mod.CompositeEvalMetric):
        leaves = []
        for child in metric.metrics:
            leaves.extend(_metric_leaves(child))
        return leaves
    if not metric.supports_device_update():
        raise CompiledStepUnsupported(
            "metric %s (%s) has no traced_update device twin"
            % (metric.name, type(metric).__name__))
    return [metric]


class _ShardInfo:
    """Static layout of a ``shard_update=True`` step (docs/PERF.md "Sharded
    weight update"): the 1-D dp mesh, per-parameter flat/padded metas
    (parallel/zero.py), the wire-format threshold (None = fp32 reduce), and
    the ``r:`` aux key per parameter when the 2-bit codec is on."""

    def __init__(self, mesh, dp, wire, metas, residual_keys):
        self.mesh = mesh
        self.dp = dp
        self.wire = wire            # quantization threshold, or None
        self.metas = metas          # pkey -> parallel.zero.ParamMeta
        self.residual_keys = residual_keys   # pkey -> "r:<name>"

    def state_spec(self, key):
        """The PartitionSpec a state entry holds in steady state: optimizer
        leaves live flat-sharded over dp (the ZeRO 1/N win), residual rows
        shard over the replica axis, everything else is replicated."""
        from jax.sharding import PartitionSpec as P
        if key.startswith("o:"):
            return P("dp")
        if key.startswith("r:"):
            return P("dp", None)
        return P()


def _resolve_donate(donate, ctx):
    if donate != "auto":
        return bool(donate)
    # CPU XLA cannot alias donated buffers — requesting donation there only
    # produces a "donated buffers were not usable" warning per compile.
    # Key on the STEP's device, not jax.default_backend(): a cpu-bound
    # module in a TPU-backed process must not request donation either.
    if ctx is not None:
        try:
            return ctx.jax_device().platform != "cpu"
        except Exception:
            pass
    import jax
    return jax.default_backend() != "cpu"


# ---------------------------------------------------------------------------
# the step
# ---------------------------------------------------------------------------

class CompiledTrainStep:
    """One-dispatch training over a window of ``steps_per_call`` batches.

    Construction is via :meth:`from_module` / :meth:`from_block`.  The
    instance owns a flat ``state`` dict of NDArray handles (``p:`` params,
    ``a:`` executor aux, ``o:`` optimizer-state leaves, ``m:`` metric
    accumulators) — the SAME handles the module/block reads — all registered
    as CachedOp aux, so every dispatch writes the new values back in place.
    """

    def __init__(self, microstep, state_nd, optimizer, opt_bindings,
                 opt_indices, metrics, metric_keys, n_inputs, keys_per_step,
                 steps_per_call, ctx, donate, owner=None, shard=None):
        if steps_per_call < 1:
            raise ValueError("steps_per_call must be >= 1")
        self._shard = shard
        self._microstep = microstep
        self.state = state_nd
        self._state_names = sorted(state_nd)
        self._optimizer = optimizer
        self._opt_bindings = opt_bindings
        self._opt_indices = opt_indices
        self._metrics = metrics
        self._metric_keys = metric_keys
        self._n_inputs = n_inputs
        self._keys_per_step = max(1, keys_per_step)
        self.steps_per_call = steps_per_call
        self._ctx = ctx
        self._owner = owner
        flags = {"donate_params": True} if _resolve_donate(donate, ctx) \
            else {}
        self.cached_op = CachedOp(self._make_forward_fn(), state_nd,
                                  aux_names=tuple(state_nd), flags=flags)  # mxmem: nodonate(donate='auto' resolves per backend at dispatch: CPU XLA cannot alias, accelerator backends donate via donate_params — see _resolve_donate)

    # -- trace ----------------------------------------------------------
    def _make_forward_fn(self):
        microstep = self._microstep
        state_names = self._state_names
        opt_bindings = self._opt_bindings
        metrics = self._metrics
        metric_keys = self._metric_keys
        opt = self._optimizer
        n_keys = self._keys_per_step

        shard = self._shard

        def apply_optimizer(carry, new_carry, grads, lr_t, t_t):
            """Run the optimizer's own (traced) update kernels over NDArray
            wrappers of the carry values; harvest the mutated handles."""
            if shard is not None:
                return apply_optimizer_sharded(carry, new_carry, grads,
                                               lr_t, t_t)
            staged = []
            for index, pkey, template, leaf_keys in opt_bindings:
                weight = NDArray(new_carry.get(pkey, carry[pkey]))
                grad = NDArray(grads[pkey])
                leaves = iter([NDArray(carry[k]) for k in leaf_keys])
                state = _rebuild_state(template, leaves)
                staged.append((index, pkey, weight, grad, state, leaf_keys))
            with _step_hyperparams(opt, lr_t, t_t):
                for index, pkey, weight, grad, state, leaf_keys in staged:
                    opt.update_multi_precision(index, weight, grad, state)
            for index, pkey, weight, grad, state, leaf_keys in staged:
                new_carry[pkey] = weight._data
                for key, leaf in zip(leaf_keys, _state_leaf_nds(state)):
                    new_carry[key] = leaf._data

        def apply_optimizer_sharded(carry, new_carry, grads, lr_t, t_t):
            """The ZeRO variant: ONE shard_map region updates every
            parameter's flat 1/N slice on its owning replica (optimizer
            state enters as true dp-sharded vectors, so the in_specs are
            free slicing, not resharding), then all-gathers the updated
            shards.  For elementwise optimizers this is bitwise the full
            update (docs/PERF.md).  With the 2-bit wire format on, each
            replica EF-quantizes the full flat gradient against its own
            residual row and the int8 codes cross the wire reduce-scattered
            as int32."""
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P
            from jax.experimental.shard_map import shard_map
            from ..parallel.collectives import allgather
            from ..parallel.zero import (flatten_param, unflatten_param,
                                         quantized_reduce_scatter)
            axis = "dp"
            wire_t = shard.wire
            repl = NamedSharding(shard.mesh, P())
            wf, gf, sf, rf = [], [], [], []
            for index, pkey, template, leaf_keys in opt_bindings:
                meta = shard.metas[pkey]
                # pin the raw gradient (and weight) REPLICATED before it
                # feeds the shard_map: without the constraint GSPMD
                # back-propagates the region's P("dp") in_specs into the
                # vjp itself and partitions the backward reductions —
                # different summation order, so grads drift a ulp from the
                # replicated program and the bitwise parity gate breaks
                w_full = jax.lax.with_sharding_constraint(
                    new_carry.get(pkey, carry[pkey]), repl)
                g_full = jax.lax.with_sharding_constraint(grads[pkey], repl)
                wf.append(flatten_param(w_full, meta.padded))
                gf.append(flatten_param(g_full, meta.padded))
                sf.append(tuple(carry[k] for k in leaf_keys))
                if wire_t is not None:
                    rf.append(carry[shard.residual_keys[pkey]])
            wf, gf, sf, rf = tuple(wf), tuple(gf), tuple(sf), tuple(rf)

            def region(wl, gl, sl, rl, lr_v, t_v):
                staged = []
                new_r = []
                for i, (index, pkey, template, leaf_keys) in \
                        enumerate(opt_bindings):
                    if wire_t is not None:
                        # fit-path gradients are replicated (the batch is),
                        # so the psum_scatter/dp mean of dp identical
                        # dequantized copies models exactly one quantizer
                        g_shard, r_new = quantized_reduce_scatter(
                            gl[i], rl[i][0], wire_t, axis, shard.dp)
                        new_r.append(r_new[None])
                    else:
                        g_shard = gl[i]   # in_spec P("dp") sliced it
                    weight = NDArray(wl[i])
                    grad = NDArray(g_shard)
                    leaves = iter([NDArray(v) for v in sl[i]])
                    state = _rebuild_state(template, leaves)
                    staged.append((index, weight, grad, state))
                with _step_hyperparams(opt, lr_v, t_v):
                    for index, weight, grad, state in staged:
                        opt.update_multi_precision(index, weight, grad,
                                                   state)
                out_w = tuple(allgather(weight._data, axis)
                              for _, weight, _, _ in staged)
                out_s = tuple(tuple(leaf._data
                                    for leaf in _state_leaf_nds(state))
                              for _, _, _, state in staged)
                return out_w, out_s, tuple(new_r)

            s_specs = tuple(tuple(P(axis) for _ in s) for s in sf)
            r_specs = tuple(P(axis, None) for _ in rf)
            region_sh = shard_map(
                region, mesh=shard.mesh,
                in_specs=(tuple(P(axis) for _ in wf),
                          tuple(P() if wire_t is not None else P(axis)
                                for _ in gf),
                          s_specs, r_specs, P(), P()),
                out_specs=(tuple(P() for _ in wf), s_specs, r_specs),
                check_rep=False)
            new_w, new_s, new_r = region_sh(wf, gf, sf, rf, lr_t, t_t)
            for i, (index, pkey, template, leaf_keys) in \
                    enumerate(opt_bindings):
                meta = shard.metas[pkey]
                new_carry[pkey] = unflatten_param(new_w[i], meta.shape,
                                                  meta.size)
                for key, leaf in zip(leaf_keys, new_s[i]):
                    new_carry[key] = leaf
                if wire_t is not None:
                    new_carry[shard.residual_keys[pkey]] = new_r[i]

        def body(carry, xs):
            import jax.numpy as jnp
            t_t, lr_t, keys_t = xs["t"], xs["lr"], xs["keys"]
            grads, updates, preds, labels, extra = microstep(
                carry, xs["in"], keys_t)
            new_carry = dict(carry)
            new_carry.update(updates)
            apply_optimizer(carry, new_carry, grads, lr_t, t_t)
            deltas = []
            for m, (skey, ckey) in zip(metrics, metric_keys):
                stat, count = m.traced_update(labels, preds)
                new_carry[skey] = carry[skey] + stat
                new_carry[ckey] = carry[ckey] + count
                deltas += [stat, count]
            if extra is not None:
                y = extra
            elif deltas:
                y = jnp.stack([jnp.asarray(d, jnp.float32) for d in deltas])
            else:
                y = jnp.float32(0.0)
            return new_carry, y

        # the compiled fit step's declared worst case: params + grads +
        # optimizer slots live at once, plus the sharded-update region's
        # full-weight gather temps (the symbolic sites MEM_MAP catalogs)
        # mxmem: budget(hbm=1GB)
        def forward_fn(p, t_nd, lr_nd, *input_nds):
            import jax
            import jax.numpy as jnp
            from .. import random as _random

            window = int(t_nd.shape[0])
            carry = {k: p[k]._data for k in state_names}
            in_vals = [x._data for x in input_nds]
            # one key row per (microstep, rng site), all derived from the
            # CachedOp's per-call key input (random.key_override is active)
            keys = jnp.stack([
                jnp.stack([_random.next_key() for _ in range(n_keys)])
                for _ in range(window)])
            if window == 1:
                carry, y = body(carry, {
                    "t": t_nd._data[0], "lr": lr_nd._data[0],
                    "keys": keys[0], "in": [v[0] for v in in_vals]})
                ys = jnp.asarray(y)[None]
            else:
                carry, ys = jax.lax.scan(body, carry, {
                    "t": t_nd._data, "lr": lr_nd._data, "keys": keys,
                    "in": in_vals})
            if shard is not None:
                # pin every carried output to its canonical steady-state
                # sharding: without the constraint GSPMD may pick a
                # different output layout than the inputs arrived with,
                # and step 2 would silently recompile on the changed
                # input shardings (a stealth recompile cache_stats cannot
                # see — its signature is shapes/dtypes only)
                from jax.sharding import NamedSharding
                carry = {k: jax.lax.with_sharding_constraint(
                             v, NamedSharding(shard.mesh,
                                              shard.state_spec(k)))
                         for k, v in carry.items()}
            for k in state_names:
                p[k]._set_data(carry[k])
            return NDArray(ys)

        return forward_fn

    # -- dispatch -------------------------------------------------------
    def _hyper_vectors(self, window):
        opt = self._optimizer
        base = opt.num_update
        ts, lrs = [], []
        for k in range(1, window + 1):
            t = base + k
            ts.append(float(t))
            lrs.append(float(opt.lr_scheduler(t))
                       if opt.lr_scheduler is not None else float(opt.lr))
        if self._shard is not None:
            # every step input must live on the mesh: a vector committed to
            # a single device cannot enter the same jit as dp-sharded state
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ..ndarray import from_jax
            repl = NamedSharding(self._shard.mesh, P())
            return (from_jax(jax.device_put(_np.asarray(ts, _np.float32),
                                            repl), ctx=self._ctx),
                    from_jax(jax.device_put(_np.asarray(lrs, _np.float32),
                                            repl), ctx=self._ctx))
        from ..ndarray import array
        return (array(_np.asarray(ts, _np.float32), ctx=self._ctx),
                array(_np.asarray(lrs, _np.float32), ctx=self._ctx))

    def _advance_counts(self, window):
        opt = self._optimizer
        for index in self._opt_indices:
            count = opt._index_update_count.get(
                index, opt.begin_num_update) + window
            opt._index_update_count[index] = count
            opt.num_update = max(count, opt.num_update)

    def run_window(self, batches_io):  # mxflow: hot (compiled train step)
        """Train on a window of 1..steps_per_call batches in ONE dispatch.

        ``batches_io``: one tuple of input NDArrays per batch, in the
        step's input order (data..., then labels...).  Returns the step's
        per-microstep output array WITHOUT fetching it (shape [W] losses
        for from_block steps, [W, 2*n_metrics] accumulator deltas for
        from_module steps)."""
        import jax.numpy as jnp
        window = len(batches_io)
        if not 1 <= window <= self.steps_per_call:
            raise ValueError("window of %d batches vs steps_per_call=%d"
                             % (window, self.steps_per_call))
        if self._n_inputs is not None and \
                len(batches_io[0]) != self._n_inputs:
            raise ValueError("batch provides %d inputs, step expects %d"
                             % (len(batches_io[0]), self._n_inputs))
        t_nd, lr_nd = self._hyper_vectors(window)
        stacked = []
        for j in range(len(batches_io[0])):
            vals = [b[j]._data for b in batches_io]
            val = jnp.stack(vals)
            if self._shard is not None:
                # replicate the window onto the mesh (the shard_update fit
                # path keeps the batch replicated — the sharding is of the
                # UPDATE and optimizer state, docs/PERF.md)
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P
                val = jax.device_put(
                    val, NamedSharding(self._shard.mesh, P()))
            stacked.append(_wrap(val, ctx=self._ctx))
        with autograd.train_mode():
            out = self.cached_op(self.state, t_nd, lr_nd, *stacked)
        self._advance_counts(window)
        if self._owner is not None:
            self._owner._params_dirty = True
        return out

    def step(self, *inputs):
        """Single-batch convenience over :meth:`run_window`."""
        return self.run_window([tuple(inputs)])

    def sync_metric(self):
        """Fetch the on-device metric accumulators into their EvalMetric
        objects and zero them.  This is a host sync — the ONLY one the
        compiled path performs — so call it at metric_interval boundaries
        or epoch end, never per batch."""
        for m, (skey, ckey) in zip(self._metrics, self._metric_keys):
            stat = float(_np.asarray(self.state[skey].asnumpy()))  # mxflow: sync-ok(metric boundary: the one sanctioned fetch of the compiled path)
            count = float(_np.asarray(self.state[ckey].asnumpy()))  # mxflow: sync-ok(metric boundary: the one sanctioned fetch of the compiled path)
            if stat or count:
                m._device_accumulate(stat, count)
            with autograd.pause():
                # one fresh buffer per slot: sharing one zero across slots
                # would alias state entries and break buffer donation
                # ("attempt to donate the same buffer twice")
                self.state[skey]._set_data(self._committed_zero())
                self.state[ckey]._set_data(self._committed_zero())

    def _committed_zero(self):
        """A device-committed f32 scalar zero.  The steady-state accumulator
        buffers are jit outputs (committed to their device); resetting with
        an UNcommitted constant would flip the jit cache key and silently
        recompile the whole step on the next window."""
        import jax
        if self._shard is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            dev = NamedSharding(self._shard.mesh, P())
        elif self._ctx is not None:
            dev = self._ctx.jax_device()
        else:
            dev = jax.devices()[0]
        # a fresh numpy scalar per call: jnp constants can be cached, and a
        # shared buffer across state slots would defeat per-slot donation
        return jax.device_put(_np.zeros((), _np.float32), dev)

    def cache_stats(self):
        """The underlying CachedOp's per-signature compile counters."""
        return self.cached_op.cache_stats()

    # ------------------------------------------------------------------
    # frontends
    # ------------------------------------------------------------------
    @classmethod
    def from_module(cls, module, eval_metric=None, steps_per_call=1,
                    donate="auto", shard_update=False, wire_format=None,
                    wire_threshold=0.5, residual_store=None):
        """Capture a bound Module's forward+backward+update as one CachedOp.

        State handles are the executor's own ``arg_dict``/``aux_dict``
        entries and the updater's state arrays — so ``get_params()``,
        ``save_optimizer_states()`` and crash-resume (docs/ROBUSTNESS.md)
        see exactly what the step trains, and a run killed mid-epoch
        resumes bitwise like the eager path.

        ``shard_update=True`` builds the step over the default 1-D dp mesh
        (all local devices): parameters/aux replicate across the mesh while
        optimizer state converts IN PLACE to flat dp-sharded vectors
        (1/N bytes per replica — ZeRO-1/2), and the update runs per-shard
        inside a shard_map region (bitwise-equal to the replicated step for
        elementwise optimizers).  The SAME updater state handles now hold
        the flat vectors, so save/load_optimizer_states and crash-resume
        keep working bitwise — a restored flat vector is recognized by its
        padded size and re-placed sharded.  ``wire_format="2bit"`` adds the
        error-feedback quantized gradient reduce, with per-replica residual
        rows riding as ``r:`` aux entries keyed in ``residual_store`` (one
        shared :class:`~mxnet_tpu.gradient_compression.ResidualStore`; by
        default the module's own, so residuals carry across fit calls)."""
        handles_fn = getattr(module, "_compiled_step_handles", None)
        if handles_fn is None:
            raise CompiledStepUnsupported(
                "%s has no compiled-step support" % type(module).__name__)
        h = handles_fn()
        exe = h["executor"]
        opt = h["optimizer"]
        updater = h["updater"]
        if updater is None:
            raise CompiledStepUnsupported("no local updater")
        _check_optimizer(opt)
        if wire_format not in (None, "2bit"):
            raise ValueError("unknown wire_format %r (supported: '2bit')"
                             % (wire_format,))
        if wire_format is not None and not shard_update:
            raise ValueError("wire_format=%r requires shard_update=True"
                             % (wire_format,))
        shard_mesh = None
        if shard_update:
            if not getattr(opt, "elementwise", False):
                raise CompiledStepUnsupported(
                    "optimizer %s is not elementwise: the ZeRO sharded "
                    "update runs the update rule on flat 1/N parameter "
                    "slices, which is only the full update for per-element "
                    "rules" % type(opt).__name__)
            from ..parallel import make_mesh
            shard_mesh = make_mesh()
        metrics = _metric_leaves(eval_metric)

        param_names = [n for n in h["param_names"] if n in exe.arg_names]
        input_names = list(h["data_names"]) + list(h["label_names"])
        for req_name in h["data_names"]:
            if req_name not in exe.arg_names:
                raise CompiledStepUnsupported(
                    "data input %r is not a graph argument" % req_name)
        wrt_names = [n for n in param_names
                     if exe.grad_req.get(n, "null") not in ("null",)]
        for n in wrt_names:
            if exe.grad_req[n] != "write":
                raise CompiledStepUnsupported(
                    "grad_req=%r for %r (only 'write' is capturable)"
                    % (exe.grad_req[n], n))
        if not wrt_names:
            raise CompiledStepUnsupported("no trainable parameters")

        fn = exe._build_fn(True)
        n_rng = exe._n_rng
        aux_update_names = list(exe._aux_update_names)
        aux_names = list(exe.aux_names)
        arg_names = list(exe.arg_names)

        # ensure optimizer state exists under the eager updater's indices so
        # save/load_optimizer_states and resume interoperate unchanged
        name_to_index = {n: i for i, n in enumerate(param_names)}
        for n in wrt_names:
            index = name_to_index[n]
            if index not in updater.states:
                updater.states[index] = \
                    opt.create_state_multi_precision(index, exe.arg_dict[n])
                updater.states_synced[index] = True
            elif not updater.states_synced.get(index, True):
                updater.states[index] = updater._to_nd(
                    updater.states[index], exe.arg_dict[n].context)
                updater.states_synced[index] = True

        state_nd = {}
        for n in param_names:
            state_nd["p:" + n] = exe.arg_dict[n]
        for n in aux_names:
            state_nd["a:" + n] = exe.aux_dict[n]
        opt_bindings = []
        opt_indices = []
        for n in wrt_names:
            index = name_to_index[n]
            template = updater.states[index]
            leaf_keys = ["o:%s:%d" % (n, i)
                         for i in range(len(_state_leaf_nds(template)))]
            for key, leaf in zip(leaf_keys, _state_leaf_nds(template)):
                state_nd[key] = leaf
            opt_bindings.append((index, "p:" + n, template, leaf_keys))
            opt_indices.append(index)

        shard = None
        if shard_mesh is not None:
            shard = cls._shard_state(
                state_nd, opt_bindings, exe, shard_mesh, wire_format,
                wire_threshold, residual_store, h)
        metric_keys = cls._metric_state(state_nd, metrics, h["context"],
                                        mesh=shard_mesh)

        input_pos = {n: i for i, n in enumerate(input_names)}
        label_idx = [input_pos[n] for n in h["label_names"]]
        wrt_pos = {n: i for i, n in enumerate(wrt_names)}

        def microstep(carry, batch_vals, keys_t):
            import jax
            import jax.numpy as jnp
            aux_vals = [carry["a:" + n] for n in aux_names]

            def arg_vals(wrt_vals):
                vals = []
                for n in arg_names:
                    if n in input_pos:
                        vals.append(batch_vals[input_pos[n]])
                    elif n in wrt_pos:
                        vals.append(wrt_vals[wrt_pos[n]])
                    else:
                        vals.append(carry["p:" + n])
                return vals

            def f_wrt(*wv):
                return tuple(fn(arg_vals(wv), aux_vals, keys_t))

            outs, vjp = jax.vjp(f_wrt, *[carry["p:" + n] for n in wrt_names])
            n_graph = len(outs) - len(aux_update_names)
            # the fit loop's backward() contract: ones cotangents on every
            # graph output, zeros on the appended BN running-stat tail
            cts = tuple(jnp.ones_like(o) for o in outs[:n_graph]) + \
                tuple(jnp.zeros_like(o) for o in outs[n_graph:])
            grad_vals = vjp(cts)
            grads = {"p:" + n: g for n, g in zip(wrt_names, grad_vals)}
            updates = {"a:" + n: v
                       for n, v in zip(aux_update_names, outs[n_graph:])}
            preds = list(outs[:n_graph])
            labels = [batch_vals[i] for i in label_idx]
            return grads, updates, preds, labels, None

        return cls(microstep, state_nd, opt, opt_bindings, opt_indices,
                   metrics, metric_keys, len(input_names), n_rng,
                   steps_per_call, h["context"], donate, owner=module,
                   shard=shard)

    @staticmethod
    def _shard_state(state_nd, opt_bindings, exe, mesh, wire_format,
                     wire_threshold, residual_store, h):
        """Re-place the step's state for shard_update mode, IN PLACE on the
        live handles: params/aux replicate over the mesh (a single-device-
        committed array cannot enter the same jit as mesh-sharded state),
        optimizer-state leaves flatten+pad to dp-sharded vectors (the
        updater now holds — and checkpoints — the flat form; a leaf already
        flat from a resumed checkpoint is re-placed bitwise), and the wire
        format's per-replica residual rows are created (or adopted from the
        shared ResidualStore) as ``r:`` aux entries."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..ndarray import from_jax
        from ..parallel.zero import (param_meta, check_flat_state,
                                     flatten_param)

        dp = int(mesh.shape["dp"])
        repl = NamedSharding(mesh, P())
        vec = NamedSharding(mesh, P("dp"))
        row = NamedSharding(mesh, P("dp", None))

        for key, nd in state_nd.items():
            if key.startswith(("p:", "a:")):
                nd._set_data(jax.device_put(nd._data, repl))

        metas, residual_keys = {}, {}
        store = None
        if wire_format == "2bit":
            store = residual_store
            if store is None:
                make_store = h.get("residual_store")
                store = make_store() if make_store is not None else None
            if store is None:
                from ..gradient_compression import ResidualStore
                store = ResidualStore()
        for index, pkey, template, leaf_keys in opt_bindings:
            name = pkey[2:]
            weight = exe.arg_dict[name]
            meta = param_meta(name, weight._data, dp)
            metas[pkey] = meta
            for key in leaf_keys:
                leaf = state_nd[key]
                padded = check_flat_state(name, int(leaf._data.size),
                                          meta.size, dp)
                flat = flatten_param(leaf._data.reshape(-1), padded)
                leaf._set_data(jax.device_put(flat, vec))
            if store is not None:
                rkey = "r:" + name

                def make_residual(meta=meta, dtype=weight._data.dtype):
                    return from_jax(
                        jax.device_put(
                            jnp.zeros((dp, meta.padded), dtype), row),
                        ctx=h["context"])

                res_nd = store.get_or_create(name, make_residual)
                if tuple(res_nd.shape) != (dp, meta.padded):
                    raise ValueError(
                        "sharded-update flattener: residual for parameter "
                        "%r has shape %s; expected (%d, %d) for dp=%d"
                        % (name, tuple(res_nd.shape), dp, meta.padded, dp))
                # adopt a carried-over residual onto this mesh (bitwise)
                res_nd._set_data(jax.device_put(res_nd._data, row))
                state_nd[rkey] = res_nd
                residual_keys[pkey] = rkey
        return _ShardInfo(mesh, dp,
                          wire_threshold if wire_format == "2bit" else None,
                          metas, residual_keys)

    @classmethod
    def from_block(cls, block, loss_fn, optimizer, n_inputs=1,
                   eval_metric=None, steps_per_call=1, donate="auto"):
        """Capture a gluon block + explicit loss + optimizer as one CachedOp.

        ``loss_fn(outputs, *labels) -> scalar NDArray`` over the block's
        outputs; ``n_inputs`` leading step inputs feed the block, the rest
        go to the loss (and metrics) as labels.  Parameter/optimizer state
        is updated in place in the block's own Parameter storage."""
        from ..gluon.block import split_param_names
        _check_optimizer(optimizer)
        metrics = _metric_leaves(eval_metric)
        params = {p.name: p for p in block.collect_params().values()}
        train_names, frozen_names = split_param_names(block)
        param_nd = {n: params[n].data() for n in params}
        ctx = next(iter(param_nd.values())).context if param_nd else None

        state_nd = {"p:" + n: param_nd[n] for n in params}
        opt_bindings = []
        for n in train_names:
            template = optimizer.create_state_multi_precision(n, param_nd[n])
            leaf_keys = ["o:%s:%d" % (n, i)
                         for i in range(len(_state_leaf_nds(template)))]
            for key, leaf in zip(leaf_keys, _state_leaf_nds(template)):
                state_nd[key] = leaf
            opt_bindings.append((n, "p:" + n, template, leaf_keys))
        metric_keys = cls._metric_state(state_nd, metrics, ctx)

        def microstep(carry, batch_vals, keys_t):
            import jax
            from ..gluon.block import functional_call
            x_vals = batch_vals[:n_inputs]
            label_vals = batch_vals[n_inputs:]
            frozen_vals = {n: carry["p:" + n] for n in frozen_names}

            def loss_of(train_vals):
                full = dict(frozen_vals)
                full.update(train_vals)
                outs, new_aux = functional_call(block, full, *x_vals,
                                                training=True,
                                                rng_key=keys_t[0])
                loss = loss_fn([NDArray(o) for o in outs],
                               *[NDArray(v) for v in label_vals])
                # mxnet reductions keep a (1,) shape; grad needs a scalar
                return loss._data.reshape(()), (new_aux, outs)

            (loss, (new_aux, outs)), grad_vals = jax.value_and_grad(
                loss_of, has_aux=True)({n: carry["p:" + n]
                                        for n in train_names})
            grads = {"p:" + n: grad_vals[n] for n in train_names}
            updates = {"p:" + n: v for n, v in new_aux.items()}
            return grads, updates, list(outs), list(label_vals), loss

        return cls(microstep, state_nd, optimizer, opt_bindings,
                   list(train_names), metrics, metric_keys, None,
                   1, steps_per_call, ctx, donate)

    @staticmethod
    def _metric_state(state_nd, metrics, ctx, mesh=None):
        """Allocate the (sum, count) scalar accumulator pair per metric
        (device-committed, matching the steady-state jit-output buffers —
        see _committed_zero; mesh-replicated under shard_update)."""
        import jax
        from ..ndarray import from_jax
        metric_keys = []
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            dev = NamedSharding(mesh, P())
        else:
            dev = ctx.jax_device() if ctx is not None else jax.devices()[0]
        for j, _m in enumerate(metrics):
            skey, ckey = "m:%d:s" % j, "m:%d:n" % j
            for key in (skey, ckey):
                state_nd[key] = from_jax(
                    jax.device_put(_np.zeros((), _np.float32), dev), ctx=ctx)
            metric_keys.append((skey, ckey))
        return metric_keys
